"""Slice-aware multi-host mesh layout (parallel/distributed.py).

The grid-building logic is unit-tested against mocked device lists carrying
slice/process metadata; the mesh builders are integration-tested on the
spoofed single-slice CPU devices (where they must agree with the plain
builders); and the multi-process path is EXECUTED for real by
``test_two_process_split_eval_matches_single_process``: two subprocesses join
a localhost coordinator (gloo CPU collectives), shard the split eval's data
axis across processes, and must reproduce the single-process PPL exactly —
including a kill-and-resume through the shared checkpoint.
"""
import dataclasses
import json
import os
import sys

import numpy as np
import pytest

import jax

from edgellm_tpu.parallel import (SplitConfig, SplitRuntime, build_stage_grid,
                                  make_multihost_sp_stage_mesh,
                                  make_multihost_stage_mesh, make_stage_mesh)


@dataclasses.dataclass(frozen=True)
class FakeDevice:
    id: int
    process_index: int
    slice_index: int

    def __repr__(self):
        return f"d{self.id}(p{self.process_index}s{self.slice_index})"


def pod(n_slices: int, hosts_per_slice: int, chips_per_host: int):
    """A fake multi-slice pod device list, deliberately shuffled."""
    devs = []
    i = 0
    for s in range(n_slices):
        for h in range(hosts_per_slice):
            for _ in range(chips_per_host):
                devs.append(FakeDevice(id=i, process_index=s * hosts_per_slice + h,
                                       slice_index=s))
                i += 1
    rng = np.random.default_rng(0)
    return [devs[j] for j in rng.permutation(len(devs))]


def test_groups_never_span_slices():
    devs = pod(n_slices=2, hosts_per_slice=2, chips_per_host=4)  # 16 devices
    grid = build_stage_grid(devs, n_stages=4, n_data=None, n_model=1)
    assert grid.shape == (4, 4, 1)
    for d in range(grid.shape[1]):
        slices = {dev.slice_index for dev in grid[:, d, :].ravel()}
        assert len(slices) == 1, f"data group {d} spans slices {slices}"


def test_data_axis_crosses_slices_stage_axis_does_not():
    devs = pod(n_slices=2, hosts_per_slice=1, chips_per_host=8)
    grid = build_stage_grid(devs, n_stages=2, n_data=None, n_model=2)
    assert grid.shape == (2, 4, 2)
    # both slices appear along data, each (stage x model) block is one slice
    data_slices = [grid[0, d, 0].slice_index for d in range(4)]
    assert set(data_slices) == {0, 1}
    # intra-slice multi-host stages are allowed (ICI-connected within a slice)
    multi_host = pod(n_slices=1, hosts_per_slice=2, chips_per_host=2)
    grid = build_stage_grid(multi_host, n_stages=4, n_data=1, n_model=1)
    assert {d.process_index for d in grid.ravel()} == {0, 1}


def test_group_spanning_slice_rejected():
    devs = pod(n_slices=2, hosts_per_slice=1, chips_per_host=3)  # 3 per slice
    with pytest.raises(ValueError, match="span slices"):
        build_stage_grid(devs, n_stages=2, n_data=None, n_model=1)


def test_wrong_n_data_rejected():
    devs = pod(n_slices=1, hosts_per_slice=1, chips_per_host=8)
    with pytest.raises(ValueError, match="n_data=3"):
        build_stage_grid(devs, n_stages=2, n_data=3, n_model=1)


def test_deterministic_ordering():
    """The grid must not depend on the incoming device-list order (every
    process must build the SAME mesh or shard_map diverges)."""
    devs = pod(n_slices=2, hosts_per_slice=2, chips_per_host=2)
    grids = [build_stage_grid(list(perm), 2, None, 1)
             for perm in (devs, devs[::-1], sorted(devs, key=lambda d: -d.id))]
    for g in grids[1:]:
        assert (g == grids[0]).all()


def test_multihost_stage_mesh_on_single_slice_agrees_with_plain():
    """On the spoofed (single-slice) CPU devices the slice-aware mesh has the
    same axes and drives the split runtime to identical outputs (device
    placement within the slice may differ — both layouts are ICI-local)."""
    import jax.numpy as jnp

    from edgellm_tpu.models import init_params, tiny_config

    mesh = make_multihost_stage_mesh(2, n_data=2, n_model=2)
    plain = make_stage_mesh(2, n_data=2, n_model=2)
    assert dict(mesh.shape) == dict(plain.shape)
    assert sorted(d.id for d in mesh.devices.ravel()) == \
        sorted(d.id for d in plain.devices.ravel())

    cfg = tiny_config("qwen2", num_layers=4, hidden_size=32, num_heads=4,
                      vocab_size=128)
    params = init_params(cfg, jax.random.key(1))
    ids = jnp.asarray(np.random.default_rng(2).integers(0, 128, (2, 16)))
    rt = SplitRuntime(cfg, SplitConfig(cuts=(1,), hop_codecs=("int8_per_token",)),
                      mesh)
    rt_plain = SplitRuntime(cfg, SplitConfig(cuts=(1,),
                                             hop_codecs=("int8_per_token",)),
                            plain)
    np.testing.assert_allclose(
        np.asarray(rt.forward(rt.place_params(params), ids)),
        np.asarray(rt_plain.forward(rt_plain.place_params(params), ids)),
        atol=1e-6, rtol=1e-6)


def test_multihost_sp_stage_mesh():
    mesh = make_multihost_sp_stage_mesh(2, 4)
    assert dict(mesh.shape) == {"stage": 2, "seq": 4}
    devs = pod(n_slices=2, hosts_per_slice=1, chips_per_host=4)
    with pytest.raises(ValueError, match="exactly"):
        make_multihost_sp_stage_mesh(2, 2, devices=devs)  # 2 groups -> ambiguous


def test_initialize_distributed_wires_jax(monkeypatch):
    import edgellm_tpu.parallel.distributed as dist

    calls = []
    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    n = dist.initialize_distributed("host:1234", num_processes=4, process_id=1)
    assert calls == [{"coordinator_address": "host:1234", "num_processes": 4,
                      "process_id": 1}]
    assert n == jax.process_count()
    dist.initialize_distributed()  # idempotent: no second call
    assert len(calls) == 1


def test_initialize_without_coordinator_degrades_to_single_process(monkeypatch):
    import edgellm_tpu.parallel.distributed as dist

    monkeypatch.setattr(dist, "_initialized", False)
    for k in ("SLURM_NTASKS", "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")  # one host: fine

    def no_coordinator(**kw):
        raise ValueError("coordinator_address should be defined.")

    monkeypatch.setattr(jax.distributed, "initialize", no_coordinator)
    with pytest.warns(UserWarning, match="single process"):
        assert dist.initialize_distributed() == 1

    # explicit args must still surface the failure
    monkeypatch.setattr(dist, "_initialized", False)
    with pytest.raises(ValueError):
        dist.initialize_distributed("host:1", num_processes=2, process_id=0)


def test_cluster_env_failure_still_raises(monkeypatch):
    """Auto-detect failure INSIDE a real multi-host launch must not silently
    degrade into N independent single-process runs."""
    import edgellm_tpu.parallel.distributed as dist

    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: (_ for _ in ())
                        .throw(ValueError("coordinator_address should be defined.")))
    monkeypatch.setenv("SLURM_NTASKS", "4")
    with pytest.raises(ValueError, match="coordinator_address"):
        dist.initialize_distributed()


def test_multihost_hostnames_list_still_raises(monkeypatch):
    import edgellm_tpu.parallel.distributed as dist

    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-0,host-1")
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: (_ for _ in ())
                        .throw(ValueError("coordinator_address should be defined.")))
    with pytest.raises(ValueError, match="coordinator_address"):
        dist.initialize_distributed()


@pytest.mark.parametrize("var", ["OMPI_COMM_WORLD_SIZE", "PMI_SIZE",
                                 "WORLD_SIZE", "SLURM_NTASKS"])
def test_world_size_launchers_still_raise(monkeypatch, var):
    """mpirun/PMI/torchrun-style world-size vars count as a cluster launch:
    auto-detect failure must raise, not degrade to N process-0 runs."""
    import edgellm_tpu.parallel.distributed as dist

    monkeypatch.setattr(dist, "_initialized", False)
    for k in ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE", "WORLD_SIZE",
              "TPU_WORKER_HOSTNAMES", "JAX_COORDINATOR_ADDRESS",
              "JAX_NUM_PROCESSES", "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv(var, "2")
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: (_ for _ in ())
                        .throw(ValueError("coordinator_address should be defined.")))
    with pytest.raises(ValueError, match="coordinator_address"):
        dist.initialize_distributed()

    # size 1 is not a cluster: degrade normally
    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setenv(var, "1")
    with pytest.warns(UserWarning, match="single process"):
        assert dist.initialize_distributed() == 1


def test_runtime_error_coordinator_also_degrades(monkeypatch):
    """JAX version drift: a RuntimeError mentioning the coordinator (rather
    than ValueError/'coordinator_address') still takes the single-host path."""
    import edgellm_tpu.parallel.distributed as dist

    monkeypatch.setattr(dist, "_initialized", False)
    for k in ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE", "WORLD_SIZE",
              "TPU_WORKER_HOSTNAMES", "JAX_COORDINATOR_ADDRESS",
              "JAX_NUM_PROCESSES", "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: (_ for _ in ())
                        .throw(RuntimeError("no coordinator configured")))
    with pytest.warns(UserWarning, match="single process"):
        assert dist.initialize_distributed() == 1

    # a coordinator CONNECT failure is a broken launch, never a degrade
    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: (_ for _ in ())
                        .throw(RuntimeError(
                            "failed to connect to coordinator at 10.0.0.2:1234")))
    with pytest.raises(RuntimeError, match="failed to connect"):
        dist.initialize_distributed()


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn_workers(out_dir, max_chunks=None, nprocs=2):
    """Launch one multiproc_worker.py per rank against a fresh localhost
    coordinator; returns the per-rank CompletedProcess list."""
    import subprocess

    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    args = lambda r: [sys.executable, worker, str(r), str(nprocs), str(port),
                      str(out_dir)] + ([str(max_chunks)] if max_chunks else [])
    # worker output goes to files, not pipes: a rank that out-writes the OS
    # pipe buffer while the parent drains a sibling would block mid-collective
    # and deadlock the group until the timeout
    logs = [open(os.path.join(out_dir, f"worker_{r}.log"), "a+")
            for r in range(nprocs)]
    procs = [subprocess.Popen(args(r), env=env, stdout=logs[r],
                              stderr=subprocess.STDOUT, text=True)
             for r in range(nprocs)]
    done = []
    try:
        for p in procs:
            p.wait(timeout=600)
    finally:
        for p in procs:  # never orphan the peer when one rank hangs/dies
            if p.poll() is None:
                p.kill()
        for r, (p, log) in enumerate(zip(procs, logs)):
            log.seek(0)
            done.append((p.returncode, log.read()))
            log.close()
    for rc, out in done:
        assert rc == 0, f"worker failed (rc={rc}):\n{out[-4000:]}"
    return done


def test_two_process_split_eval_matches_single_process(tmp_path):
    """The multi-process (DCN) path, EXECUTED: 2 subprocesses, localhost
    coordinator, gloo CPU collectives, the split eval's data axis spanning
    processes. Covers fetch_global's process_allgather branch and the
    process-0-only checkpoint/metrics writes — the final PPL must equal a
    single-process run, through a kill-and-resume."""
    from edgellm_tpu.models import tiny_config, init_params
    from edgellm_tpu.eval.split_eval import run_split_eval

    # phase 1: stop after 2 chunks ("kill"); phase 2: resume to completion
    _spawn_workers(tmp_path, max_chunks=2)
    ckpt = json.load(open(tmp_path / "ckpt.json"))
    assert ckpt["chunks"] == 2
    _spawn_workers(tmp_path)

    results = [json.load(open(tmp_path / f"result_{r}.json")) for r in (0, 1)]
    # SPMD: every rank holds identical accumulators
    assert results[0]["ppl"] == results[1]["ppl"]
    assert results[0]["chunks"] == results[1]["chunks"]

    # single-process oracle on this process's spoofed devices (same math, no
    # process boundary); the workload definition is shared with the worker
    from multiproc_worker import workload

    cfg_kwargs, (seed, length), run_kwargs = workload()
    cfg = tiny_config("qwen2", **cfg_kwargs)
    params = init_params(cfg, jax.random.key(0))
    corpus = np.random.default_rng(seed).integers(0, cfg.vocab_size, length)
    single = run_split_eval(cfg, params, corpus, window_batch=2, **run_kwargs)
    assert results[0]["chunks"] == single["chunks"]
    np.testing.assert_allclose(results[0]["ppl"], single["ppl"],
                               rtol=1e-5, atol=1e-6)
    # process-0-only writes: checkpoint + metrics exist and are consistent
    lines = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    finals = [rec for rec in lines if rec.get("final")]
    np.testing.assert_allclose(finals[-1]["ppl"], single["ppl"],
                               rtol=1e-5, atol=1e-6)
