"""Micro-batch pipelined split schedule: identity, validation, counters.

The pipelined schedule (``PipelineConfig(num_microbatches=M)``) is a latency
optimization, never a numerics change: every entry point that runs through
``run_pipeline_stages_microbatched`` / ``_carry_microbatched`` must produce
BIT-identical outputs to the sequential schedule at any M, because each
µ-batch's rows see exactly the same per-row compute and the same per-row
codec math (pipelining is refused outright for codecs whose scales couple
rows across the batch). That identity is asserted here for forward, the
contiguous-cache decode loop, and the batcher's ragged paged decode — at
num_microbatches in {1, 2, 4} per the ISSUE acceptance — alongside the
schedule's own bookkeeping (per-µ-batch fault counters, occupancy/bubble
accounting) and the validation surface (divisibility, batch-variant codecs,
stage-only mesh).

Also here (ISSUE satellite): >= 3-stage DECODE coverage — ``generate_split``
and the batcher's paged decode at cuts=(1, 3) with mixed codecs, clean and
through a retrying faulty link, token-identical to single-device
``generate`` (forward-only 3-stage coverage lives in test_split.py).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.codecs.faults import FaultConfig, LinkPolicy
from edgellm_tpu.models import tiny_config, init_params, forward
from edgellm_tpu.parallel import (PipelineConfig, SplitConfig, SplitRuntime,
                                  make_stage_mesh)
from edgellm_tpu.serve import generate
from edgellm_tpu.serve.batching import BatchingConfig, ContinuousBatcher
from edgellm_tpu.serve.decode import generate_split

CFG = tiny_config("qwen2", num_layers=6, hidden_size=32, num_heads=4,
                  vocab_size=128)
SPLIT = SplitConfig(cuts=(1, 3),
                    hop_codecs=("int8_per_token", "int8_per_token"))
MIXED = SplitConfig(cuts=(1, 3), hop_codecs=("int4_global", "int8_per_token"))


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(1))


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 3:
        pytest.skip("needs >= 3 devices (spoofed CPU mesh)")
    return make_stage_mesh(3)


@pytest.fixture(scope="module")
def ids():
    rng = np.random.default_rng(5)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (4, 12)))


# ---------- PipelineConfig ----------

def test_pipeline_config_validation():
    with pytest.raises(ValueError):
        PipelineConfig(num_microbatches=0)
    pc = PipelineConfig(num_microbatches=4)
    assert pc.enabled and not PipelineConfig().enabled
    assert pc.validate_batch(8) == 2
    with pytest.raises(ValueError):
        pc.validate_batch(6)
    with pytest.raises(ValueError):
        pc.validate_batch(0)


def test_pipeline_summary_accounting():
    s = PipelineConfig(num_microbatches=4).summary(n_stages=3)
    # T = M + n - 1 unroll steps; each stage busy for M of them
    assert s["unroll_steps"] == 6
    assert s["stage_occupancy"] == pytest.approx([4 / 6] * 3)
    assert s["bubble_fraction_schedule"] == pytest.approx(2 / 6)
    assert s["bubble_fraction_sequential"] == pytest.approx(2 / 3)
    # more µ-batches strictly shrink the schedule bubble
    s8 = PipelineConfig(num_microbatches=8).summary(n_stages=3)
    assert s8["bubble_fraction_schedule"] < s["bubble_fraction_schedule"]


def test_pipeline_validation_errors(params, mesh):
    # batch-variant codec: per-batch scales would change per-µ-batch
    with pytest.raises(ValueError, match="batch"):
        SplitRuntime(CFG, MIXED, mesh,
                     pipeline=PipelineConfig(num_microbatches=2))
    # batch not divisible by the µ-batch count
    rt = SplitRuntime(CFG, SPLIT, mesh,
                      pipeline=PipelineConfig(num_microbatches=4))
    placed = rt.place_params(params)
    bad = jnp.zeros((3, 8), jnp.int32)
    with pytest.raises(ValueError, match="multiple"):
        rt.forward(placed, bad)
    # data-parallel mesh: µ-batching and batch-sharding both slice the batch
    dmesh = make_stage_mesh(2, n_data=2)
    with pytest.raises(ValueError):
        SplitRuntime(CFG, SplitConfig(cuts=(3,),
                                      hop_codecs=("int8_per_token",)),
                     dmesh, pipeline=PipelineConfig(num_microbatches=2))


# ---------- tentpole identity: pipelined == sequential ----------

@pytest.mark.parametrize("m", [1, 2, 4])
def test_pipelined_forward_bit_identical(params, mesh, ids, m):
    base = SplitRuntime(CFG, SPLIT, mesh)
    rt = SplitRuntime(CFG, SPLIT, mesh,
                      pipeline=PipelineConfig(num_microbatches=m))
    placed = base.place_params(params)
    np.testing.assert_array_equal(
        np.asarray(base.forward(placed, ids)),
        np.asarray(rt.forward(placed, ids)))


@pytest.mark.parametrize("m", [1, 2, 4])
def test_pipelined_generate_split_token_identical(params, mesh, ids, m):
    base = SplitRuntime(CFG, SPLIT, mesh)
    rt = SplitRuntime(CFG, SPLIT, mesh,
                      pipeline=PipelineConfig(num_microbatches=m))
    placed = base.place_params(params)
    want = np.asarray(generate_split(base, placed, ids, 8, capacity=20))
    st: dict = {}
    got = np.asarray(generate_split(rt, placed, ids, 8, capacity=20,
                                    stats=st))
    np.testing.assert_array_equal(want, got)
    if m > 1:
        assert st["pipeline"]["num_microbatches"] == m
        assert st["pipeline"]["enabled"]


def test_pipelined_paged_decode_token_identical(params, mesh):
    bcfg = BatchingConfig(max_slots=4, num_pages=16, page_size=4,
                          pages_per_slot=6)
    results = []
    for pipe in (None, PipelineConfig(num_microbatches=2),
                 PipelineConfig(num_microbatches=4)):
        rt = SplitRuntime(CFG, SPLIT, mesh, pipeline=pipe)
        bat = ContinuousBatcher(CFG, params, bcfg, split_runtime=rt,
                                placed_params=rt.place_params(params))
        rng = np.random.default_rng(3)
        for i in range(4):
            bat.submit(rng.integers(1, CFG.vocab_size,
                                    size=4 + i).astype(np.int32),
                       6, rng_seed=i)
        results.append({k: v.tolist() for k, v in bat.run().items()})
    assert results[0] == results[1] == results[2]


# ---------- per-µ-batch fault counters ----------

def test_microbatch_fault_counters(params, mesh, ids):
    m = 2
    rt = SplitRuntime(CFG, SPLIT, mesh,
                      faults=FaultConfig(drop_rate=0.3, seed=0),
                      policy=LinkPolicy(max_retries=5),
                      pipeline=PipelineConfig(num_microbatches=m))
    placed = rt.place_params(params)
    for step in range(4):
        rt.forward(placed, ids, fault_step=step)
    per_mb = rt.microbatch_counters()
    totals = rt.link_counters()
    assert set(per_mb) == set(totals)
    for name, rows in per_mb.items():
        assert rows.shape == (m, len(SPLIT.cuts))
        # the µ-batch rows decompose the aggregate stream exactly
        np.testing.assert_array_equal(rows.sum(axis=0),
                                      np.asarray(totals[name]))
    # every µ-batch genuinely hopped: 4 forwards x 2 hops each
    np.testing.assert_array_equal(per_mb["hops"], np.full((m, 2), 4))


def test_microbatch_fault_replay_deterministic(params, mesh, ids):
    outs = []
    for _ in range(2):
        rt = SplitRuntime(CFG, SPLIT, mesh,
                          faults=FaultConfig(drop_rate=0.3, seed=0),
                          policy=LinkPolicy(max_retries=5),
                          pipeline=PipelineConfig(num_microbatches=2))
        placed = rt.place_params(params)
        outs.append(np.asarray(rt.forward(placed, ids, fault_step=1)))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_unpipelined_runtime_has_no_microbatch_counters(params, mesh):
    rt = SplitRuntime(CFG, SPLIT, mesh,
                      faults=FaultConfig(drop_rate=0.3, seed=0),
                      policy=LinkPolicy(max_retries=5))
    assert rt.microbatch_counters() is None


def test_pipelined_eval_pads_partial_tail_group(params, mesh):
    """7 windows at window_batch=2 leave a 1-window tail group: the eval must
    pad it up to the µ-batch grid (zero loss weight) instead of handing the
    pipelined schedule an indivisible batch. Scored-token totals must match
    the sequential run exactly; NLL to float tolerance (the padded window's
    rows compute in a different batch shape, same as data-axis padding)."""
    from edgellm_tpu.eval.split_eval import run_split_eval

    rng = np.random.default_rng(7)
    toks = rng.integers(0, CFG.vocab_size, size=80).astype(np.int32)
    kw = dict(cuts=(1, 3), hop_codecs=("int8_per_token",) * 2,
              max_length=16, stride=8, window_batch=2, time_hops=False)
    seq = run_split_eval(CFG, params, toks, mesh=mesh, **kw)
    pipe = run_split_eval(CFG, params, toks, mesh=mesh,
                          pipeline=PipelineConfig(num_microbatches=2), **kw)
    assert pipe["n_tokens"] == seq["n_tokens"]
    assert pipe["chunks"] == seq["chunks"]
    assert pipe["pad_fraction"] > 0.0  # the tail really was padded
    assert pipe["total_nll"] == pytest.approx(seq["total_nll"], rel=1e-5)
    assert pipe["pipeline"]["num_microbatches"] == 2


def test_pipelined_eval_refuses_batch_variant_ladder(params, mesh):
    from edgellm_tpu.eval.split_eval import run_split_eval

    toks = np.arange(64, dtype=np.int32) % CFG.vocab_size
    with pytest.raises(ValueError, match="ladder"):
        run_split_eval(CFG, params, toks, mesh=mesh,
                       cuts=(1, 3), hop_codecs=("int8_per_token",) * 2,
                       max_length=16, stride=8, window_batch=2,
                       faults=FaultConfig(drop_rate=0.1, seed=0),
                       link_policy=LinkPolicy(max_retries=1,
                                              tiers=("int4_global",)),
                       pipeline=PipelineConfig(num_microbatches=2))


# ---------- satellite: >= 3-stage decode vs single-device generate ----------

def test_three_stage_generate_split_matches_generate(params, mesh):
    rng = np.random.default_rng(5)
    ids1 = jnp.asarray(rng.integers(0, CFG.vocab_size, (1, 8)))
    want = np.asarray(generate(CFG, params, ids1, 12, capacity=20))
    rt = SplitRuntime(CFG, MIXED, mesh)
    got = np.asarray(generate_split(rt, rt.place_params(params), ids1, 12,
                                    capacity=20))
    np.testing.assert_array_equal(want, got)


def test_three_stage_generate_split_retrying_faulty_link(params, mesh):
    """A lossy-but-retried link at cuts=(1, 3): every drop recovers within
    the retry budget (seed-pinned), so the tokens stay identical to the
    single-device greedy decode while the counters prove real retries."""
    rng = np.random.default_rng(5)
    ids1 = jnp.asarray(rng.integers(0, CFG.vocab_size, (1, 8)))
    want = np.asarray(generate(CFG, params, ids1, 12, capacity=20))
    rt = SplitRuntime(CFG, MIXED, mesh,
                      faults=FaultConfig(drop_rate=0.3, seed=0),
                      policy=LinkPolicy(max_retries=5))
    got = np.asarray(generate_split(rt, rt.place_params(params), ids1, 12,
                                    capacity=20))
    c = {k: np.asarray(v) for k, v in rt.link_counters().items()}
    assert c["retried"].sum() > 0 and c["recovered"].sum() > 0
    assert c["substituted"].sum() == 0  # parity below is only meaningful then
    np.testing.assert_array_equal(want, got)


def test_three_stage_paged_decode_matches_generate(params, mesh):
    bcfg = BatchingConfig(max_slots=4, num_pages=20, page_size=4,
                          pages_per_slot=6)
    rt = SplitRuntime(CFG, MIXED, mesh)
    bat = ContinuousBatcher(CFG, params, bcfg, split_runtime=rt,
                            placed_params=rt.place_params(params))
    rng = np.random.default_rng(9)
    prompts = {}
    for i in range(4):
        p = rng.integers(1, CFG.vocab_size, size=4 + i).astype(np.int32)
        prompts[bat.submit(p, 6, rng_seed=i)] = p
    results = bat.run()
    for sid, p in prompts.items():
        want = np.asarray(generate(CFG, params, jnp.asarray(p)[None], 6,
                                   capacity=p.size + 6))[0]
        np.testing.assert_array_equal(want, np.asarray(results[sid]))
