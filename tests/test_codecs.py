"""Codec unit tests: the jit-safe vectorized codecs must match naive oracle
implementations that follow the reference's algorithms literally (greedy loops,
per-channel Python loops, fancy-indexed in-place edits) — see SURVEY.md section 2.1
and ``/root/reference/Experiments/Qwen2-0.5B/qwen_layer_wise.py:54-152``.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.codecs import (
    token_select_mask,
    top_rho_mask,
    int4_token_select,
    per_token_affine_int8,
    channel_wise_quant,
    CHANNEL_METHODS,
)


def _oracle_token_select_int4(hidden: np.ndarray, importance: np.ndarray, ratio: float):
    """Literal re-enactment of qwen_layer_wise.py:54-70 in numpy."""
    h = hidden.copy()
    s = h.shape[1]
    idx = np.argsort(importance, kind="stable")[: int(ratio * s)]
    if len(idx) == 0:
        return h
    sel = h[:, idx, :]
    max_val = np.max(np.abs(sel))
    scaled = np.clip(sel / max_val * 7.0, -8.0, 7.0)
    h[:, idx, :] = np.round(scaled) / 7.0 * max_val
    return h


def _oracle_top_rho(distribution: np.ndarray, threshold: float):
    """Literal greedy loop of pythia_model.py:95-109; returns quantized-token set."""
    pairs = sorted(enumerate(distribution), key=lambda x: x[1], reverse=True)
    total, n_kept = 0.0, 0
    for _, value in pairs:
        if total >= threshold:
            break
        total += value
        n_kept += 1
    return {i for i, _ in pairs[n_kept:]}


def _oracle_channel_wise(hidden: np.ndarray, method: str):
    """Literal per-channel loop of qwen_layer_wise.py:122-152."""
    h = hidden.copy()
    for c in range(h.shape[2]):
        ch = h[:, :, c]
        if method in ("channel_8", "channel_4"):
            levels = 127.0 if method == "channel_8" else 7.0
            cmax = np.max(np.abs(ch))
            h[:, :, c] = np.round(ch / cmax * levels) * cmax / levels
        elif method == "channel_1_mean":
            mean = np.mean(ch) + 1e-8
            h[:, :, c] = np.clip(np.round(ch / mean), -1, 1) * mean
        else:
            cmax = np.max(np.abs(ch))
            h[:, :, c] = np.clip(np.round(ch / cmax), -1, 1) * cmax
    return h


@pytest.fixture
def hidden(rng):
    return rng.normal(size=(2, 24, 16)).astype(np.float32)


@pytest.mark.parametrize("ratio", [0.0, 0.1, 0.25, 0.5, 0.75, 1.0])
def test_int4_token_select_matches_reference_semantics(hidden, rng, ratio):
    importance = rng.random(24).astype(np.float32)
    got = np.asarray(int4_token_select(jnp.asarray(hidden), jnp.asarray(importance), ratio))
    want = _oracle_token_select_int4(hidden, importance, ratio)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_int4_values_live_on_16_level_grid(hidden, rng):
    importance = rng.random(24).astype(np.float32)
    out = np.asarray(int4_token_select(jnp.asarray(hidden), jnp.asarray(importance), 1.0))
    max_val = np.max(np.abs(hidden))
    codes = out / max_val * 7.0
    np.testing.assert_allclose(codes, np.round(codes), atol=1e-5)
    assert codes.min() >= -8.0 - 1e-5 and codes.max() <= 7.0 + 1e-5


def test_token_select_mask_ties_break_like_stable_argsort():
    imp = jnp.asarray([0.5, 0.2, 0.2, 0.9, 0.2])
    # 3/5 is exact in binary: int(0.6000000000000001 * 5) would be fragile, so
    # pick k via an exactly-representable ratio
    mask = np.asarray(token_select_mask(imp, 3 / 5 + 1e-12, 5))  # k = 3
    # stable ascending: positions 1, 2, 4 (the tied 0.2s in original order)
    np.testing.assert_array_equal(mask, [False, True, True, False, True])


@pytest.mark.parametrize("ratio", [0, 1, 3, 5, 8, 10])
def test_top_rho_mask_matches_greedy_loop(rng, ratio):
    dist = rng.random(32).astype(np.float64)
    dist /= dist.sum()
    threshold = 1.0 - 0.1 * ratio
    mask = np.asarray(top_rho_mask(jnp.asarray(dist), threshold))
    want = _oracle_top_rho(dist, threshold)
    assert {i for i in range(32) if mask[i]} == want


@pytest.mark.parametrize("method", CHANNEL_METHODS)
def test_channel_wise_matches_reference_loop(hidden, method):
    got = np.asarray(channel_wise_quant(jnp.asarray(hidden), method))
    want = _oracle_channel_wise(hidden, method)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_token_select_k_truncates_in_float64():
    """ratio * S products just below an integer must truncate like the
    reference's float64 int(ratio * s): 0.29 * 100 = 28.999... -> k = 28, while
    a float32 floor of the traced product rounds up to 29 (the simulate-vs-wire
    one-token divergence the advisor flagged)."""
    assert int(0.29 * 100) == 28  # float64
    assert int(np.floor(np.float32(0.29) * 100)) == 29  # the traced fallback
    imp = jnp.arange(100.0)
    mask = np.asarray(token_select_mask(imp, 0.29, 100))
    assert mask.sum() == 28  # float64 truncation, not float32 floor
    # explicit k overrides agree with the wire codec's int(ratio * s)
    mask_k = np.asarray(token_select_mask(imp, 0.29, 100, k=int(0.29 * 100)))
    np.testing.assert_array_equal(mask, mask_k)


def test_per_token_affine_int8_roundtrip(hidden):
    out = np.asarray(per_token_affine_int8(jnp.asarray(hidden)))
    # error bounded by half a quantization step per token
    step = (hidden.max(-1) - hidden.min(-1)) / 255.0
    assert np.all(np.abs(out - hidden) <= step[..., None] * 0.5 + 1e-6)


def test_per_token_affine_int8_respects_mask(hidden):
    mask = np.zeros(24, bool)
    mask[3:7] = True
    out = np.asarray(per_token_affine_int8(jnp.asarray(hidden), jnp.asarray(mask)))
    np.testing.assert_array_equal(out[:, ~mask, :], hidden[:, ~mask, :])
    assert not np.allclose(out[:, mask, :], hidden[:, mask, :])


def test_codecs_are_jittable(hidden, rng):
    importance = jnp.asarray(rng.random(24).astype(np.float32))
    h = jnp.asarray(hidden)
    jit_sel = jax.jit(int4_token_select, static_argnames=())
    np.testing.assert_allclose(
        np.asarray(jit_sel(h, importance, 0.5)),
        np.asarray(int4_token_select(h, importance, 0.5)), atol=1e-6)
    jit_ch = jax.jit(channel_wise_quant, static_argnums=(1,))
    np.testing.assert_allclose(
        np.asarray(jit_ch(h, "channel_4")),
        np.asarray(channel_wise_quant(h, "channel_4")), atol=1e-6)


def test_degenerate_inputs_do_not_nan():
    h = jnp.zeros((1, 8, 4))
    imp = jnp.arange(8.0)
    assert np.isfinite(np.asarray(int4_token_select(h, imp, 0.5))).all()
    for m in CHANNEL_METHODS:
        assert np.isfinite(np.asarray(channel_wise_quant(h, m))).all()
    assert np.isfinite(np.asarray(per_token_affine_int8(h))).all()
