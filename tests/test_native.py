"""Native C++ packing library: bit-for-bit agreement with the JAX wire format
(the .so acts as an implementation-independent oracle for the packed layout)."""
import numpy as np
import pytest

import jax.numpy as jnp

from edgellm_tpu import native
from edgellm_tpu.codecs.packing import get_wire_codec, pack_ternary, unpack_ternary

pytestmark = pytest.mark.skipif(not native.is_available(),
                                reason="no C++ toolchain available")


def test_int4_encode_bitwise_matches_jax(rng):
    x = rng.normal(size=(32, 64)).astype(np.float32)
    packed_c, scales_c = native.int4_per_token_encode(x)
    want = get_wire_codec("int4_per_token").encode(jnp.asarray(x[None]))
    np.testing.assert_array_equal(packed_c, np.asarray(want["packed"][0]))
    np.testing.assert_allclose(scales_c, np.asarray(want["scale"][0, :, 0]), rtol=1e-7)


def test_int4_roundtrip_matches_jax(rng):
    x = rng.normal(size=(16, 32)).astype(np.float32)
    packed, scales = native.int4_per_token_encode(x)
    out_c = native.int4_per_token_decode(packed, scales)
    codec = get_wire_codec("int4_per_token")
    want = np.asarray(codec.decode(codec.encode(jnp.asarray(x[None]))))[0]
    np.testing.assert_allclose(out_c, want, atol=1e-6)


def test_ternary_pack_bitwise_matches_jax(rng):
    codes = rng.integers(-1, 2, size=(8, 32)).astype(np.int8)
    packed_c = native.ternary_pack(codes)
    np.testing.assert_array_equal(packed_c, np.asarray(pack_ternary(jnp.asarray(codes))))
    np.testing.assert_array_equal(native.ternary_unpack(packed_c), codes)
    np.testing.assert_array_equal(
        np.asarray(unpack_ternary(jnp.asarray(packed_c))), codes)


def test_payload_bytes_match(rng):
    assert native.int4_payload_bytes(512, 896) == \
        get_wire_codec("int4_per_token").payload_bytes((1, 512, 896))


def test_constant_and_zero_rows():
    x = np.zeros((4, 16), np.float32)
    x[1] = 3.25
    packed, scales = native.int4_per_token_encode(x)
    out = native.int4_per_token_decode(packed, scales)
    np.testing.assert_allclose(out[0], 0.0)
    np.testing.assert_allclose(out[1], 3.25, rtol=1e-6)


def test_int8_per_channel_bitwise_matches_jax(rng):
    x = rng.normal(size=(24, 32)).astype(np.float32)
    q_c, scales_c = native.int8_per_channel_encode(x)
    want = get_wire_codec("int8_per_channel").encode(jnp.asarray(x[None]))
    np.testing.assert_array_equal(q_c, np.asarray(want["q"][0]))
    np.testing.assert_allclose(scales_c, np.asarray(want["scale"]).reshape(-1),
                               rtol=1e-7)
    out = native.int8_per_channel_decode(q_c, scales_c)
    codec = get_wire_codec("int8_per_channel")
    np.testing.assert_allclose(out, np.asarray(codec.decode(want))[0], atol=1e-6)


def test_int4_per_channel_bitwise_matches_jax(rng):
    x = rng.normal(size=(24, 32)).astype(np.float32)
    packed_c, scales_c = native.int4_per_channel_encode(x)
    want = get_wire_codec("int4_per_channel").encode(jnp.asarray(x[None]))
    np.testing.assert_array_equal(packed_c, np.asarray(want["packed"][0]))
    out = native.int4_per_channel_decode(packed_c, scales_c)
    codec = get_wire_codec("int4_per_channel")
    np.testing.assert_allclose(out, np.asarray(codec.decode(want))[0], atol=1e-6)
