"""Native C++ packing library: bit-for-bit agreement with the JAX wire format
(the .so acts as an implementation-independent oracle for the packed layout)."""
import numpy as np
import pytest

import jax.numpy as jnp

from edgellm_tpu import native
from edgellm_tpu.codecs.packing import get_wire_codec, pack_ternary, unpack_ternary

pytestmark = pytest.mark.skipif(not native.is_available(),
                                reason="no C++ toolchain available")


def test_int4_encode_bitwise_matches_jax(rng):
    x = rng.normal(size=(32, 64)).astype(np.float32)
    packed_c, scales_c = native.int4_per_token_encode(x)
    want = get_wire_codec("int4_per_token").encode(jnp.asarray(x[None]))
    np.testing.assert_array_equal(packed_c, np.asarray(want["packed"][0]))
    np.testing.assert_allclose(scales_c, np.asarray(want["scale"][0, :, 0]), rtol=1e-7)


def test_int4_roundtrip_matches_jax(rng):
    x = rng.normal(size=(16, 32)).astype(np.float32)
    packed, scales = native.int4_per_token_encode(x)
    out_c = native.int4_per_token_decode(packed, scales)
    codec = get_wire_codec("int4_per_token")
    want = np.asarray(codec.decode(codec.encode(jnp.asarray(x[None]))))[0]
    np.testing.assert_allclose(out_c, want, atol=1e-6)


def test_ternary_pack_bitwise_matches_jax(rng):
    codes = rng.integers(-1, 2, size=(8, 32)).astype(np.int8)
    packed_c = native.ternary_pack(codes)
    np.testing.assert_array_equal(packed_c, np.asarray(pack_ternary(jnp.asarray(codes))))
    np.testing.assert_array_equal(native.ternary_unpack(packed_c), codes)
    np.testing.assert_array_equal(
        np.asarray(unpack_ternary(jnp.asarray(packed_c))), codes)


def test_payload_bytes_match(rng):
    assert native.int4_payload_bytes(512, 896) == \
        get_wire_codec("int4_per_token").payload_bytes((1, 512, 896))


def test_constant_and_zero_rows():
    x = np.zeros((4, 16), np.float32)
    x[1] = 3.25
    packed, scales = native.int4_per_token_encode(x)
    out = native.int4_per_token_decode(packed, scales)
    np.testing.assert_allclose(out[0], 0.0)
    np.testing.assert_allclose(out[1], 3.25, rtol=1e-6)


def test_int8_per_channel_bitwise_matches_jax(rng):
    x = rng.normal(size=(24, 32)).astype(np.float32)
    q_c, scales_c = native.int8_per_channel_encode(x)
    want = get_wire_codec("int8_per_channel").encode(jnp.asarray(x[None]))
    np.testing.assert_array_equal(q_c, np.asarray(want["q"][0]))
    np.testing.assert_allclose(scales_c, np.asarray(want["scale"]).reshape(-1),
                               rtol=1e-7)
    out = native.int8_per_channel_decode(q_c, scales_c)
    codec = get_wire_codec("int8_per_channel")
    np.testing.assert_allclose(out, np.asarray(codec.decode(want))[0], atol=1e-6)


def test_int4_per_channel_bitwise_matches_jax(rng):
    x = rng.normal(size=(24, 32)).astype(np.float32)
    packed_c, scales_c = native.int4_per_channel_encode(x)
    want = get_wire_codec("int4_per_channel").encode(jnp.asarray(x[None]))
    np.testing.assert_array_equal(packed_c, np.asarray(want["packed"][0]))
    out = native.int4_per_channel_decode(packed_c, scales_c)
    codec = get_wire_codec("int4_per_channel")
    np.testing.assert_allclose(out, np.asarray(codec.decode(want))[0], atol=1e-6)


@pytest.mark.parametrize("ratio", [0.0, 0.25, 0.5, 1.0])
def test_selective_decode_matches_jax_bitwise(rng, ratio):
    """The C++ oracle reassembles a JAX-encoded selective_int4 payload —
    including deriving the high-row placement from the int16 low-index side
    channel — bit-identically to the CPU JAX decode (a TPU decode may differ
    by 1 ulp on the dequantized low rows; the suite runs on CPU)."""
    from edgellm_tpu.codecs.packing import selective_int4

    h = jnp.asarray(rng.normal(size=(2, 16, 32)).astype(np.float32))
    imp = jnp.asarray(rng.random(16).astype(np.float32))
    codec = selective_int4(ratio, "bf16")
    payload = codec.encode(h, imp)
    want = np.asarray(codec.decode(payload))

    got = native.selective_int4_decode(
        np.asarray(payload["low"]),
        float(np.asarray(payload["scale"])[0]),
        np.asarray(payload["high"]).view(np.uint16),
        np.asarray(payload["order"]))
    np.testing.assert_array_equal(got, want)


def test_selective_decode_rejects_bad_payloads(rng):
    """Wire payloads arrive off-fabric: per-row orders, corrupt indices, and
    mismatched batches must be rejected before the C++ scatter."""
    from edgellm_tpu.codecs.packing import selective_int4

    h = jnp.asarray(rng.normal(size=(2, 16, 32)).astype(np.float32))
    per_row = selective_int4(0.5, "bf16").encode(
        h, jnp.asarray(rng.random((2, 16)).astype(np.float32)))
    with pytest.raises(ValueError, match="shared-ordering"):
        native.selective_int4_decode(
            np.asarray(per_row["low"]), 1.0,
            np.asarray(per_row["high"]).view(np.uint16),
            np.asarray(per_row["order"]))

    shared = selective_int4(0.5, "bf16").encode(
        h, jnp.asarray(rng.random(16).astype(np.float32)))
    low = np.asarray(shared["low"])
    high = np.asarray(shared["high"]).view(np.uint16)
    bad = np.asarray(shared["order"]).copy()
    bad[0] = 99  # out of range for S=16
    with pytest.raises(ValueError, match="corrupt"):
        native.selective_int4_decode(low, 1.0, high, bad)
    dup = np.asarray(shared["order"]).copy()
    dup[0] = dup[1]
    with pytest.raises(ValueError, match="corrupt"):
        native.selective_int4_decode(low, 1.0, high, dup)
    with pytest.raises(ValueError, match="batch"):
        native.selective_int4_decode(low, 1.0, high[:1],
                                     np.asarray(shared["order"]))
