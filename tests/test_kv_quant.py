"""KV-at-rest compression: quantized paged pools, packed round-trips.

The load-bearing claims: (1) the ``fp`` tier IS the pre-quantization data
path — same pool type, same compiled step, token-identical output; (2) on
quantized tiers every page movement (COW fork, defrag, eviction, adopt,
checkpoint) is a BYTE move of packed codes + scales, never a requantize,
so gather -> adopt round-trips are bit-exact across any pool geometry and
a stream's tokens survive eviction/restore unchanged; (3) cross-tier
restore is REFUSED, not transcoded. Capacity math: the same byte budget
buys proportionally more pages at a packed tier, which is the whole point.
"""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.models import init_params, tiny_config
from edgellm_tpu.models.flash_attention import (dequantize_kv_rows,
                                                paged_decode_attention,
                                                paged_decode_attention_quant,
                                                quantize_kv_rows)
from edgellm_tpu.models.paged_kv import (KV_PAGE_CODECS, OutOfPages,
                                         PagedKVCache, PrefixCacheConfig,
                                         kv_page_bytes,
                                         num_pages_for_bytes,
                                         resolve_kv_codec)
from edgellm_tpu.serve.batching import BatchingConfig, ContinuousBatcher
from edgellm_tpu.serve.decode import generate
from edgellm_tpu.serve.recovery import CheckpointError

CFG = tiny_config("qwen2", num_layers=4, hidden_size=32, num_heads=4,
                  vocab_size=128)
# fp geometry shared with tests/test_batching.py; quantized twins differ
# ONLY in the kv_codec field, so admission/span math is identical
BCFG = BatchingConfig(page_size=8, num_pages=17, max_slots=4,
                      pages_per_slot=4)
BCFG8 = dataclasses.replace(BCFG, kv_codec="int8_per_channel")
BCFG4 = dataclasses.replace(BCFG, kv_codec="int4_per_channel")

# pool-level tests use a 2-layer model: tier bookkeeping is layer-count
# independent and the materialized pages stay tiny
CFG2 = tiny_config("qwen2", num_layers=2, hidden_size=32, num_heads=4,
                   vocab_size=128)
PROMPT = list(range(100, 110))
TIERS = ("int8_per_channel", "int4_per_channel")


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(1))


def _prompt(n, seed=0):
    return np.random.default_rng(seed).integers(
        1, CFG.vocab_size, size=n).astype(np.int32)


def _solo(params, prompt, max_new, temp=0.0, seed=0):
    out = generate(CFG, params, jnp.asarray(prompt)[None], max_new,
                   capacity=BCFG.span, temperature=temp,
                   rng_key=jax.random.key(seed))
    return np.asarray(out)[0]


def _seq(n, seed):
    r = np.random.default_rng(seed)
    shape = (CFG2.num_layers, n, CFG2.num_kv_heads, CFG2.head_dim)
    return (jnp.asarray(r.standard_normal(shape), jnp.float32),
            jnp.asarray(r.standard_normal(shape), jnp.float32))


def _qcache(kv_codec, **kw):
    kw.setdefault("num_pages", 13)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_slots", 3)
    kw.setdefault("pages_per_slot", 4)
    return PagedKVCache(CFG2, kv_codec=kv_codec, **kw)


def _packed_equal(a, b, rows=None):
    for key in ("k_codes", "v_codes", "k_scale", "v_scale"):
        x, y = np.asarray(a[key]), np.asarray(b[key])
        if rows is not None:
            x, y = x[:, :rows], y[:, :rows]
        np.testing.assert_array_equal(x, y, err_msg=key)


# ---------------------------------------------------------------------------
# codec registry + capacity math
# ---------------------------------------------------------------------------


def test_codec_registry_refuses_unknown_tiers():
    with pytest.raises(ValueError, match="unknown kv_codec"):
        resolve_kv_codec("int2_per_galaxy")
    assert resolve_kv_codec("fp").quantized is False
    for t in TIERS:
        assert resolve_kv_codec(t).quantized
    with pytest.raises(ValueError, match="even head_dim"):
        KV_PAGE_CODECS["int4_per_channel"].code_lanes(7)


def test_page_bytes_and_budget_capacity_ratio():
    hd = CFG2.head_dim
    fp_row = hd * 4
    assert KV_PAGE_CODECS["fp"].row_bytes(hd) == fp_row
    assert KV_PAGE_CODECS["int8_per_channel"].row_bytes(hd) == hd + 4
    assert KV_PAGE_CODECS["int4_per_channel"].row_bytes(hd) == hd // 2 + 4
    fp_page = kv_page_bytes(CFG2, 4, "fp")
    assert fp_page == 2 * CFG2.num_layers * 4 * CFG2.num_kv_heads * fp_row
    # a fixed byte budget must buy >= 2x the pages at the packed tiers —
    # the acceptance-gate concurrency multiplier comes straight from here
    budget = 8 * fp_page
    n_fp = num_pages_for_bytes(CFG2, budget, 4, "fp")
    assert n_fp == 8
    for t in TIERS:
        assert num_pages_for_bytes(CFG2, budget, 4, t) >= 2 * n_fp
    with pytest.raises(ValueError, match="page 0 is reserved"):
        num_pages_for_bytes(CFG2, kv_page_bytes(CFG2, 4, "int4_per_channel"),
                            4, "int4_per_channel")


def test_out_of_pages_math_with_shrunken_pages():
    # same budget, same request: the fp pool refuses what int4 admits
    budget = 5 * kv_page_bytes(CFG2, 4, "fp")
    geo = dict(page_size=4, max_slots=2, pages_per_slot=8,
               materialize=False)
    fp = PagedKVCache(CFG2, num_pages=num_pages_for_bytes(
        CFG2, budget, 4, "fp"), kv_codec="fp", **geo)
    q4 = PagedKVCache(CFG2, num_pages=num_pages_for_bytes(
        CFG2, budget, 4, "int4_per_channel"), kv_codec="int4_per_channel",
        **geo)
    s = fp.alloc_slot()
    with pytest.raises(OutOfPages):
        fp.ensure(s, 20)          # 5 pages > the 4 the budget buys
    fp.check_invariants()
    for _ in range(2):            # int4: BOTH slots fit at the same bytes
        q4.ensure(q4.alloc_slot(), 20)
    q4.check_invariants()


# ---------------------------------------------------------------------------
# quantize / dequantize rows
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("tier", TIERS)
def test_quantize_roundtrip_error_bound_and_idempotence(tier):
    qmax = {"int8_per_channel": 127, "int4_per_channel": 7}[tier]
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 9, 3, CFG2.head_dim)) * 3.0,
                    jnp.float32)
    x = x.at[0, 4].set(0.0)       # an all-zero row must survive exactly
    codes, scales = quantize_kv_rows(x, tier)
    y = dequantize_kv_rows(codes, scales, tier)
    assert y.shape == x.shape and y.dtype == jnp.float32
    # per-row absmax scaling: error <= half a quantization step, per row
    step = np.asarray(scales)[..., None] / qmax
    assert (np.abs(np.asarray(x - y)) <= 0.5 * step + 1e-6).all()
    np.testing.assert_array_equal(np.asarray(y[0, 4]), 0.0)
    assert float(scales[0, 4].max()) == 0.0
    # requantizing the dequantized rows reproduces the SAME bytes — the
    # property every byte-move path (COW, defrag, checkpoint) leans on
    codes2, scales2 = quantize_kv_rows(y, tier)
    np.testing.assert_array_equal(np.asarray(codes2), np.asarray(codes))
    np.testing.assert_allclose(np.asarray(scales2), np.asarray(scales),
                               rtol=1e-6)


@pytest.mark.parametrize("tier", TIERS)
def test_paged_quant_fallback_matches_dequantized_pool(tier):
    # the quant decode-attention entrypoint == dequantize the WHOLE pool
    # then the plain paged path, exactly (same contract graphlint executes)
    npg, pgs, ms, pps = 5, 8, 2, 2
    rng = np.random.default_rng(3)
    kv = (npg * pgs, CFG2.num_kv_heads, CFG2.head_dim)
    kq, ks = quantize_kv_rows(
        jnp.asarray(rng.standard_normal(kv), jnp.float32), tier)
    vq, vs = quantize_kv_rows(
        jnp.asarray(rng.standard_normal(kv), jnp.float32), tier)
    hdc = kq.shape[-1]
    q = jnp.asarray(rng.standard_normal(
        (ms, 1, CFG2.num_heads, CFG2.head_dim)), jnp.float32)
    tab = jnp.asarray(rng.permutation(np.arange(1, npg))[:ms * pps]
                      .reshape(ms, pps).astype(np.int32))
    lens = jnp.asarray([pgs + 3, pgs - 2], jnp.int32)
    got = paged_decode_attention_quant(
        q, kq.reshape(npg, pgs, -1, hdc), vq.reshape(npg, pgs, -1, hdc),
        ks.reshape(npg, pgs, -1), vs.reshape(npg, pgs, -1), tab, lens,
        kv_codec=tier)
    kf = dequantize_kv_rows(kq, ks, tier)
    vf = dequantize_kv_rows(vq, vs, tier)
    ref = paged_decode_attention(
        q, kf.reshape(npg, pgs, -1, CFG2.head_dim),
        vf.reshape(npg, pgs, -1, CFG2.head_dim), tab, lens)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


# ---------------------------------------------------------------------------
# quantized pool surgery: adopt / gather / COW / defrag / state_dict
# ---------------------------------------------------------------------------


def test_packed_gather_adopt_roundtrip_across_geometry():
    cache = _qcache("int8_per_channel")
    s = cache.alloc_slot()
    k, v = _seq(10, 0)
    cache.adopt(s, k, v, 10)
    cache.check_invariants()
    packed = cache.gather_slot_packed(s)
    # the dequantized view agrees with dequantizing the packed bytes
    # (to fp rounding — XLA may fuse the scale multiply differently)
    g = cache.gather_slot(s)
    np.testing.assert_allclose(
        g["k"], np.asarray(dequantize_kv_rows(
            jnp.asarray(packed["k_codes"]), jnp.asarray(packed["k_scale"]),
            "int8_per_channel")), rtol=1e-6, atol=1e-7)
    # adopt_packed into a DIFFERENT pool geometry: bytes land unchanged
    other = _qcache("int8_per_channel", num_pages=5, page_size=8,
                    max_slots=2, pages_per_slot=2)
    s2 = other.alloc_slot()
    other.adopt_packed(s2, packed["k_codes"], packed["v_codes"],
                       packed["k_scale"], packed["v_scale"],
                       int(packed["length"]))
    other.check_invariants()
    _packed_equal(other.gather_slot_packed(s2), packed)
    # the packed API is tier-gated in both directions
    fp = _qcache("fp")
    sf = fp.alloc_slot()
    fp.adopt(sf, k, v, 10)
    with pytest.raises(ValueError, match="quantized tiers"):
        fp.gather_slot_packed(sf)
    with pytest.raises(ValueError, match="quantized tiers"):
        fp.adopt_packed(sf, packed["k_codes"], packed["v_codes"],
                        packed["k_scale"], packed["v_scale"], 10)


def test_quant_cow_fork_is_a_byte_move():
    pcfg = PrefixCacheConfig(enabled=True, min_shared_block=1)
    cache = _qcache("int4_per_channel", prefix_cache=pcfg)
    s0 = cache.alloc_slot()
    k0, v0 = _seq(10, 0)
    cache.adopt(s0, k0, v0, 10)
    assert cache.register_prefix(s0, PROMPT) == 3
    donor = cache.gather_slot_packed(s0)
    s1 = cache.alloc_slot()
    assert cache.share_prefix(s1, PROMPT + [111, 112], max_tokens=11) == 10
    k1, v1 = _seq(2, 1)
    cache.adopt_rows(s1, k1, v1, 10, 12)   # forks the shared partial page
    cache.check_invariants()
    assert cache.prefix_counters["cow_forks"] == 1
    # the fork copied codes AND scales: the sharer's first 10 rows are
    # byte-identical to the donor's, and the donor is untouched
    _packed_equal(cache.gather_slot_packed(s1), donor, rows=10)
    _packed_equal(cache.gather_slot_packed(s0), donor)


def test_defrag_with_packed_pages_preserves_bytes():
    cache = _qcache("int8_per_channel")
    slots, snaps = [], {}
    for i, n in enumerate((10, 7, 12)):
        s = cache.alloc_slot()
        k, v = _seq(n, i)
        cache.adopt(s, k, v, n)
        slots.append(s)
    cache.free_slot(slots[1])     # punch holes mid-pool
    for s in (slots[0], slots[2]):
        snaps[s] = cache.gather_slot_packed(s)
    assert cache.defrag() > 0
    cache.check_invariants()
    for s, snap in snaps.items():
        _packed_equal(cache.gather_slot_packed(s), snap)


def test_state_dict_roundtrip_and_tier_refusal():
    cache = _qcache("int8_per_channel")
    s = cache.alloc_slot()
    k, v = _seq(9, 4)
    cache.adopt(s, k, v, 9)
    state = cache.state_dict()
    assert state["kv_codec"] == "int8_per_channel"
    assert {"k_codes", "v_codes", "k_scale", "v_scale"} <= set(state)
    twin = _qcache("int8_per_channel")
    twin.load_state_dict(state)
    twin.check_invariants()
    _packed_equal(twin.gather_slot_packed(s), cache.gather_slot_packed(s))
    np.testing.assert_array_equal(np.asarray(twin.pool.k),
                                  np.asarray(cache.pool.k))
    # cross-tier restore is refused in BOTH directions, never transcoded
    with pytest.raises(ValueError, match="transcoding is refused"):
        _qcache("fp").load_state_dict(state)
    fp = _qcache("fp")
    sf = fp.alloc_slot()
    fp.adopt(sf, k, v, 9)
    fp_state = fp.state_dict()
    # fp checkpoints keep the pre-quantization key set
    assert "kv_codec" not in fp_state and {"k", "v"} <= set(fp_state)
    with pytest.raises(ValueError, match="transcoding is refused"):
        _qcache("int8_per_channel").load_state_dict(fp_state)


# ---------------------------------------------------------------------------
# quantized continuous batching
# ---------------------------------------------------------------------------


def test_fp_tier_is_default_and_token_identical(params):
    assert BatchingConfig().kv_codec == "fp"
    with pytest.raises(ValueError, match="unknown kv_codec"):
        BatchingConfig(kv_codec="float13")
    bat = ContinuousBatcher(CFG, params, dataclasses.replace(
        BCFG, kv_codec="fp"))
    assert not hasattr(bat.pool.pool, "k_scale")   # plain fp PagePool
    p = _prompt(7, 40)
    sid = bat.submit(p, 5, temperature=0.7, rng_seed=3)
    np.testing.assert_array_equal(bat.run()[sid],
                                  _solo(params, p, 5, 0.7, 3))


def test_mixed_tiers_coexist_in_process(params):
    # one process, three batchers at three tiers over the SAME geometry:
    # jit caches are keyed by tier, pools never mix, everything drains
    streams = [dict(prompt=_prompt(6, 50), max_new=5, temp=0.0, seed=7),
               dict(prompt=_prompt(11, 51), max_new=4, temp=0.8, seed=8)]
    for bcfg in (BCFG, BCFG8, BCFG4):
        bat = ContinuousBatcher(CFG, params, bcfg)
        sids = [bat.submit(s["prompt"], s["max_new"],
                           temperature=s["temp"], rng_seed=s["seed"])
                for s in streams]
        results = bat.run()
        for sid, s in zip(sids, streams):
            assert len(results[sid]) == s["max_new"]
        rep = bat.report()
        assert rep["finished"] == len(streams) and rep["evicted"] == 0
        if bcfg.kv_codec == "fp":   # fp tier stays bit-identical to solo
            for sid, s in zip(sids, streams):
                np.testing.assert_array_equal(
                    results[sid], _solo(params, s["prompt"], s["max_new"],
                                        s["temp"], s["seed"]))


def test_quant_eviction_readmit_bit_identical(params):
    # pool too small for all three quant streams: the evicted stream's
    # pages leave as PACKED bytes and come back as the same bytes, so its
    # tokens match the uncontended run of the SAME tier exactly
    streams = [dict(prompt=_prompt(15, 60), max_new=8, temp=0.0, seed=1),
               dict(prompt=_prompt(14, 61), max_new=8, temp=0.9, seed=2),
               dict(prompt=_prompt(13, 62), max_new=8, temp=0.0, seed=3)]
    ref = {}
    roomy = ContinuousBatcher(CFG, params, BCFG8)
    for i, s in enumerate(streams):
        sid = roomy.submit(s["prompt"], s["max_new"],
                           temperature=s["temp"], rng_seed=s["seed"])
        ref[i] = roomy.run()[sid]
    tight = ContinuousBatcher(CFG, params, dataclasses.replace(
        BCFG8, num_pages=8))          # 7 allocatable pages
    sids = [tight.submit(s["prompt"], s["max_new"], temperature=s["temp"],
                         rng_seed=s["seed"]) for s in streams]
    results = tight.run()
    assert tight.report()["evicted"] > 0
    for i, sid in enumerate(sids):
        np.testing.assert_array_equal(results[sid], ref[i])


def test_quant_checkpoint_restore_across_geometry(params, tmp_path):
    p = _prompt(7, 70)
    ref = ContinuousBatcher(CFG, params, BCFG8)
    ref_sid = ref.submit(p, 8, temperature=0.6, rng_seed=42)
    want = ref.run()[ref_sid]
    bat = ContinuousBatcher(CFG, params, BCFG8)
    sid = bat.submit(p, 8, temperature=0.6, rng_seed=42)
    for _ in range(4):
        bat.step()
    path = bat.checkpoint_stream(sid, str(tmp_path / "q.ckpt"))
    # a DIFFERENT pool geometry at the same tier: the payload is packed
    # rows, not pages, so the restored stream finishes bit-identically
    other = ContinuousBatcher(CFG, params, dataclasses.replace(
        BCFG8, page_size=4, num_pages=33, max_slots=2, pages_per_slot=8))
    rid = other.restore_stream(path)
    np.testing.assert_array_equal(other.run()[rid], want)


def test_quant_checkpoint_cross_tier_restore_refused(params, tmp_path):
    bat = ContinuousBatcher(CFG, params, BCFG8)
    sid = bat.submit(_prompt(5, 80), 4)
    bat.step()
    qpath = bat.checkpoint_stream(sid, str(tmp_path / "q.ckpt"))
    with pytest.raises(CheckpointError, match="transcoding is refused"):
        ContinuousBatcher(CFG, params, BCFG).restore_stream(qpath)
    with pytest.raises(CheckpointError, match="transcoding is refused"):
        ContinuousBatcher(CFG, params, BCFG4).restore_stream(qpath)
    fbat = ContinuousBatcher(CFG, params, BCFG)
    fsid = fbat.submit(_prompt(5, 81), 4)
    fbat.step()
    fpath = fbat.checkpoint_stream(fsid, str(tmp_path / "f.ckpt"))
    with pytest.raises(CheckpointError, match="transcoding is refused"):
        ContinuousBatcher(CFG, params, BCFG8).restore_stream(fpath)


# ---------------------------------------------------------------------------
# split runtime: per-stage quant pools move the same bytes
# ---------------------------------------------------------------------------


def test_split_quant_pool_packed_roundtrip(params):
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    from edgellm_tpu.parallel import SplitConfig, SplitRuntime, \
        make_stage_mesh

    rt = SplitRuntime(CFG, SplitConfig(cuts=(2,), hop_codecs=("fp32",)),
                      make_stage_mesh(2))
    placed = rt.place_params(params)
    ps, npg = 8, 9
    host = PagedKVCache(CFG, num_pages=npg, page_size=ps, max_slots=2,
                        pages_per_slot=4, materialize=False,
                        kv_codec="int8_per_channel")
    pool = rt.init_paged_pool(npg, ps, kv_codec="int8_per_channel")
    prompt = _prompt(9, 90)
    _, cache = rt.prefill_decode(placed, jnp.asarray(prompt)[None], 32)
    slot = host.alloc_slot()
    host.ensure(slot, len(prompt))
    dest = host._flat_indices(slot, len(prompt))
    pool = rt.adopt_paged(pool, cache, 0, dest, len(prompt))
    host.lengths[slot] = len(prompt)
    packed = rt.gather_paged_packed(pool, dest)
    # readmit the SAME bytes at a different placement in a fresh pool
    pool2 = rt.init_paged_pool(npg, ps, kv_codec="int8_per_channel")
    host2 = PagedKVCache(CFG, num_pages=npg, page_size=ps, max_slots=2,
                         pages_per_slot=4, materialize=False,
                         kv_codec="int8_per_channel")
    host2.alloc_slot()
    s2 = host2.alloc_slot()       # slot 1: different page placement
    host2.ensure(s2, len(prompt))
    dest2 = host2._flat_indices(s2, len(prompt))
    pool2 = rt.adopt_paged_rows_packed(pool2, *packed, dest2)
    back = rt.gather_paged_packed(pool2, dest2)
    for a, b in zip(packed, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the dequantized gather form stays finite (suffix-prefill compute path)
    rows_k, rows_v = rt.gather_paged(pool, dest)
    assert np.isfinite(rows_k).all() and np.isfinite(rows_v).all()
    # the packed APIs are tier-gated on fp pools
    fpool = rt.init_paged_pool(npg, ps)
    with pytest.raises(ValueError, match="quantized"):
        rt.gather_paged_packed(fpool, dest)
    with pytest.raises(ValueError, match="quantized"):
        rt.adopt_paged_rows_packed(fpool, *packed, dest2)


# ---------------------------------------------------------------------------
# eval harness
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_kv_tier_eval_sweep_bounds(params):
    from edgellm_tpu.eval.split_eval import run_kv_tier_sweep

    corpus = np.random.default_rng(0).integers(
        1, CFG.vocab_size, size=256).astype(np.int32)
    rows = run_kv_tier_sweep(CFG, params, corpus,
                             tiers=("fp", "int8_per_channel"),
                             max_length=32, stride=32, page_size=8,
                             window_batch=2, max_chunks=2)
    by = {r["kv_codec"]: r for r in rows}
    assert by["fp"]["ppl_delta_vs_fp"] == 0.0
    assert abs(by["int8_per_channel"]["ppl_delta_vs_fp"]) < 0.01
    assert (by["int8_per_channel"]["kv_page_bytes"]
            < by["int8_per_channel"]["kv_page_bytes_fp"])
    assert all(np.isfinite(r["ppl"]) for r in rows)
