"""Continuous batching + paged KV: allocator invariants, ragged parity,
mid-flight admit/evict, jit-miss-free steady state, checkpoint/restore.

The load-bearing claim everywhere: a stream's tokens through the paged
ragged step are BIT-IDENTICAL to running it alone through ``generate``
(per-step sampling keys depend only on (seed, step index); masked padding
contributes exactly 0 to softmax; pages store the same post-rotary values
the contiguous cache stores).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.models import init_params, tiny_config
from edgellm_tpu.models.flash_attention import (decode_attention, decode_plan,
                                                paged_decode_attention)
from edgellm_tpu.models.paged_kv import (OutOfPages, OutOfSlots,
                                         PagedKVCache)
from edgellm_tpu.serve.batching import (BatchingConfig, ContinuousBatcher,
                                        _batched_sample,
                                        batched_step_cache_size)
from edgellm_tpu.serve.decode import _sample, generate
from edgellm_tpu.serve.recovery import CheckpointError

CFG = tiny_config("qwen2", num_layers=4, hidden_size=32, num_heads=4,
                  vocab_size=128)

# one shared geometry so every batcher test reuses the same compiled ragged
# step: span 32 = 4 pages x 8, the capacity generate() parity calls use too
BCFG = BatchingConfig(page_size=8, num_pages=17, max_slots=4,
                      pages_per_slot=4)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(1))


def _prompt(n, seed=0):
    return np.random.default_rng(seed).integers(
        1, CFG.vocab_size, size=n).astype(np.int32)


def _solo(params, prompt, max_new, temp=0.0, seed=0):
    out = generate(CFG, params, jnp.asarray(prompt)[None], max_new,
                   capacity=BCFG.span, temperature=temp,
                   rng_key=jax.random.key(seed))
    return np.asarray(out)[0]


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------


def _bookkeeping(num_pages=9, page_size=4, max_slots=3, pages_per_slot=3):
    return PagedKVCache(CFG, num_pages=num_pages, page_size=page_size,
                        max_slots=max_slots, pages_per_slot=pages_per_slot,
                        materialize=False)


def test_pool_alloc_free_invariants():
    pool = _bookkeeping()
    s0 = pool.alloc_slot()
    pool.ensure(s0, 5)            # 2 pages
    pool.check_invariants()
    assert len(pool._slot_pages[s0]) == 2
    assert pool.num_free_pages == 8 - 2
    s1 = pool.alloc_slot()
    pool.ensure(s1, 12)           # 3 pages (the slot max)
    pool.check_invariants()
    pool.free_slot(s0)
    pool.check_invariants()
    assert pool.num_free_pages == 8 - 3
    # ensure() must allocate nothing when it cannot cover the growth
    s2 = pool.alloc_slot()
    pool.ensure(s2, 12)
    s3 = pool.alloc_slot()
    free_before = pool.num_free_pages
    with pytest.raises(OutOfPages):
        pool.ensure(s3, 12)       # needs 3, only 2 free
    assert pool.num_free_pages == free_before
    pool.check_invariants()
    with pytest.raises(OutOfSlots):
        pool.alloc_slot()
    with pytest.raises(ValueError):
        pool.ensure(s3, pool.span + 1)


def test_trash_page_never_allocated():
    pool = _bookkeeping()
    slots = [pool.alloc_slot() for _ in range(3)]
    for s in slots:
        pool.ensure(s, 8)
        assert 0 not in pool._slot_pages[s]
    pool.check_invariants()


def test_bookkeeping_only_mode_guards():
    pool = _bookkeeping()
    assert pool.pool is None
    for call in (lambda: pool.adopt(0, None, None, 1),
                 lambda: pool.gather_slot(0),
                 pool.defrag, pool.state_dict,
                 lambda: pool.load_state_dict({})):
        with pytest.raises(ValueError, match="materialize=False"):
            call()


def test_adopt_gather_roundtrip():
    pool = PagedKVCache(CFG, num_pages=9, page_size=4, max_slots=2,
                        pages_per_slot=3)
    rng = np.random.default_rng(3)
    n = 10
    k = rng.standard_normal(
        (CFG.num_layers, n, CFG.num_kv_heads, CFG.head_dim)).astype(np.float32)
    v = rng.standard_normal(k.shape).astype(np.float32)
    slot = pool.alloc_slot()
    pool.adopt(slot, jnp.asarray(k), jnp.asarray(v), n)
    pool.check_invariants()
    back = pool.gather_slot(slot)
    assert int(back["length"]) == n
    np.testing.assert_array_equal(back["k"], k)
    np.testing.assert_array_equal(back["v"], v)


def test_defrag_preserves_content_and_compacts():
    pool = PagedKVCache(CFG, num_pages=13, page_size=4, max_slots=3,
                        pages_per_slot=4)
    rng = np.random.default_rng(5)
    shapes = {}
    for n in (7, 9, 6):
        k = rng.standard_normal((CFG.num_layers, n, CFG.num_kv_heads,
                                 CFG.head_dim)).astype(np.float32)
        v = rng.standard_normal(k.shape).astype(np.float32)
        slot = pool.alloc_slot()
        pool.adopt(slot, jnp.asarray(k), jnp.asarray(v), n)
        shapes[slot] = (k, v)
    pool.free_slot(1)             # hole in the middle of the pool
    del shapes[1]
    moved = pool.defrag()
    pool.check_invariants()
    assert moved > 0
    # allocated pages are now the low contiguous range, trash page fixed
    owned = sorted(p for pages in pool._slot_pages for p in pages)
    assert owned == list(range(1, len(owned) + 1))
    for slot, (k, v) in shapes.items():
        back = pool.gather_slot(slot)
        np.testing.assert_array_equal(back["k"], k)
        np.testing.assert_array_equal(back["v"], v)


def test_defrag_churn_page_moves_up_past_free_page():
    # regression: alloc/grow/free churn can leave an owned page whose
    # compacted destination is a HIGHER id currently on the free list
    # (here slot pages [[4], [2, 1]] with page 3 free: page 1's destination
    # is 3). The old->new map is then not invertible, and a naive inversion
    # gathered the free page's garbage into the destination — silently,
    # since check_invariants() only sees bookkeeping.
    pool = PagedKVCache(CFG, num_pages=6, page_size=4, max_slots=3,
                        pages_per_slot=2)
    rng = np.random.default_rng(11)

    def kv(n):
        k = rng.standard_normal((CFG.num_layers, n, CFG.num_kv_heads,
                                 CFG.head_dim)).astype(np.float32)
        return k, rng.standard_normal(k.shape).astype(np.float32)

    def fill(slot, n):
        k, v = kv(n)
        pool.adopt(slot, jnp.asarray(k), jnp.asarray(v), n)
        return k, v

    s0 = pool.alloc_slot()
    fill(s0, 4)                       # page [1]
    s1 = pool.alloc_slot()
    fill(s1, 4)                       # page [2]
    s2 = pool.alloc_slot()
    fill(s2, 4)                       # page [3]
    pool.free_slot(s0)                # free: [5, 4, 1]
    k1, v1 = fill(s1, 8)              # grows into page 1 -> [2, 1]
    s0 = pool.alloc_slot()
    k0, v0 = fill(s0, 4)              # pops page 4 -> [4]
    pool.free_slot(s2)                # free: [5, 3]
    assert pool._slot_pages[s0] == [4]
    assert pool._slot_pages[s1] == [2, 1]
    pool.check_invariants()

    moved = pool.defrag()
    pool.check_invariants()
    assert moved > 0
    owned = sorted(p for pages in pool._slot_pages for p in pages)
    assert owned == list(range(1, len(owned) + 1))
    for slot, (k, v) in ((s0, (k0, v0)), (s1, (k1, v1))):
        back = pool.gather_slot(slot)
        np.testing.assert_array_equal(back["k"], k)
        np.testing.assert_array_equal(back["v"], v)


# ---------------------------------------------------------------------------
# ragged step parity
# ---------------------------------------------------------------------------


def test_ragged_mixed_lengths_bit_identical_to_generate(params):
    bat = ContinuousBatcher(CFG, params, BCFG)
    streams = [  # mixed prompt lengths, remaining tokens, temperatures
        dict(prompt=_prompt(5, 1), max_new=6, temp=0.0, seed=11),
        dict(prompt=_prompt(9, 2), max_new=4, temp=0.7, seed=22),
        dict(prompt=_prompt(13, 3), max_new=8, temp=1.1, seed=33),
    ]
    sids = [bat.submit(s["prompt"], s["max_new"], temperature=s["temp"],
                       rng_seed=s["seed"]) for s in streams]
    results = bat.run()
    for sid, s in zip(sids, streams):
        np.testing.assert_array_equal(
            results[sid], _solo(params, s["prompt"], s["max_new"],
                                s["temp"], s["seed"]))
    rep = bat.report()
    assert rep["finished"] == 3 and rep["evicted"] == 0
    assert rep["jit_misses"] <= 1  # at most the one warmup compile


def test_steady_state_is_jit_miss_free(params):
    # warm the geometry's executable...
    warm = ContinuousBatcher(CFG, params, BCFG)
    warm.submit(_prompt(4), 2)
    warm.run()
    # ...then a FRESH batcher with different streams never compiles again:
    # admit/evict/fill states are traced inputs, not trace constants
    bat = ContinuousBatcher(CFG, params, BCFG)
    before = batched_step_cache_size()
    for i, (n, m) in enumerate([(3, 5), (11, 3), (7, 7), (6, 4), (9, 2)]):
        bat.submit(_prompt(n, seed=i), m, temperature=0.5 * i, rng_seed=i)
    bat.run()
    assert batched_step_cache_size() == before
    assert bat.report()["jit_misses"] == 0


def test_eviction_under_pressure_still_bit_identical(params):
    # pool too small for all three streams at once: the youngest evicts
    # mid-flight, re-queues with its gathered prefix, and STILL matches solo
    tight = BatchingConfig(page_size=8, num_pages=8, max_slots=4,
                           pages_per_slot=4)  # 7 allocatable pages
    bat = ContinuousBatcher(CFG, params, tight)
    streams = [
        dict(prompt=_prompt(15, 7), max_new=8, temp=0.0, seed=1),
        dict(prompt=_prompt(14, 8), max_new=8, temp=0.9, seed=2),
        dict(prompt=_prompt(13, 9), max_new=8, temp=0.0, seed=3),
    ]
    sids = [bat.submit(s["prompt"], s["max_new"], temperature=s["temp"],
                       rng_seed=s["seed"]) for s in streams]
    results = bat.run()
    assert bat.report()["evicted"] > 0
    for sid, s in zip(sids, streams):
        np.testing.assert_array_equal(
            results[sid], _solo(params, s["prompt"], s["max_new"],
                                s["temp"], s["seed"]))


def test_explicit_midflight_evict_resumes_identically(params):
    bat = ContinuousBatcher(CFG, params, BCFG)
    p = _prompt(6, 4)
    sid = bat.submit(p, 8, temperature=0.8, rng_seed=9)
    for _ in range(3):
        bat.step()
    bat.evict(sid)
    assert bat._streams[sid].status == "waiting"
    results = bat.run()
    np.testing.assert_array_equal(results[sid],
                                  _solo(params, p, 8, 0.8, 9))
    assert bat._streams[sid].evictions == 1


def test_max_new_tokens_one_is_prefill_only(params):
    bat = ContinuousBatcher(CFG, params, BCFG)
    p = _prompt(5, 6)
    sid = bat.submit(p, 1)
    results = bat.run()
    np.testing.assert_array_equal(results[sid], _solo(params, p, 1))


def test_run_raises_when_no_stream_can_fit(params):
    # span covers the request, but the pool never has enough free pages
    wedged = BatchingConfig(page_size=8, num_pages=3, max_slots=2,
                            pages_per_slot=4)  # 2 allocatable pages
    bat = ContinuousBatcher(CFG, params, wedged)
    bat.submit(_prompt(20), 4)    # needs 3 pages just to admit
    with pytest.raises(OutOfPages):
        bat.run()


def test_submit_validation(params):
    bat = ContinuousBatcher(CFG, params, BCFG)
    with pytest.raises(ValueError):
        bat.submit(np.zeros((0,), np.int32), 4)
    with pytest.raises(ValueError):
        bat.submit(_prompt(4), 0)
    with pytest.raises(ValueError):
        bat.submit(_prompt(4), 4, temperature=-0.1)
    with pytest.raises(ValueError):
        bat.submit(_prompt(30), 8)  # 30 + 8 - 1 > span 32


def test_trash_page_stays_finite(params):
    bat = ContinuousBatcher(CFG, params, BCFG)
    bat.submit(_prompt(5), 6)     # slots 1-3 inactive: they write page 0
    bat.run()
    assert np.isfinite(np.asarray(bat.pool.pool.k[:, 0])).all()
    assert np.isfinite(np.asarray(bat.pool.pool.v[:, 0])).all()


# ---------------------------------------------------------------------------
# checkpoint / restore
# ---------------------------------------------------------------------------


def test_checkpoint_restore_across_pool_geometry(params, tmp_path):
    p = _prompt(7, 10)
    bat = ContinuousBatcher(CFG, params, BCFG)
    sid = bat.submit(p, 8, temperature=0.6, rng_seed=42)
    for _ in range(4):
        bat.step()
    path = bat.checkpoint_stream(sid, str(tmp_path / "s.ckpt"))
    # restore into a DIFFERENT pool geometry: the payload is the contiguous
    # prefix, so any span that covers it works
    other = ContinuousBatcher(
        CFG, params, BatchingConfig(page_size=4, num_pages=17, max_slots=2,
                                    pages_per_slot=8))
    rid = other.restore_stream(path)
    results = other.run()
    np.testing.assert_array_equal(results[rid],
                                  _solo(params, p, 8, 0.6, 42))


def test_checkpoint_refuses_other_model(params, tmp_path):
    bat = ContinuousBatcher(CFG, params, BCFG)
    sid = bat.submit(_prompt(5), 4)
    bat.step()
    path = bat.checkpoint_stream(sid, str(tmp_path / "s.ckpt"))
    other_cfg = tiny_config("qwen2", num_layers=2, hidden_size=32,
                            num_heads=4, vocab_size=128)
    other = ContinuousBatcher(other_cfg, init_params(other_cfg,
                                                     jax.random.key(0)), BCFG)
    with pytest.raises(CheckpointError, match="model"):
        other.restore_stream(path)


# ---------------------------------------------------------------------------
# kernel plan gates + attention fallback
# ---------------------------------------------------------------------------


def test_decode_plan_paged_gates(monkeypatch):
    # contiguous decode has no validated kernel: always None
    assert decode_plan(256, 4, 2, 64) is None
    # paged + forced pallas: the plan dispatches on any backend
    monkeypatch.setenv("EDGELLM_ATTN", "pallas")
    assert decode_plan(64, 4, 2, 64, pages=(8, 8)) == ("paged", (8, 8))
    assert decode_plan(64, 4, 2, 64, pages=(4, 8)) is None  # pps*ps != cap
    assert decode_plan(64, 4, 2, 64, pages=(16, 4)) is None  # ps % 8
    assert decode_plan(64, 4, 2, 8, pages=(8, 8)) is None   # hd unvalidated
    monkeypatch.setenv("EDGELLM_ATTN", "xla")
    assert decode_plan(64, 4, 2, 64, pages=(8, 8)) is None
    monkeypatch.delenv("EDGELLM_ATTN")
    if jax.default_backend() != "tpu":
        # default: off-TPU the paged kernel is never earned
        assert decode_plan(64, 4, 2, 64, pages=(8, 8)) is None


def test_paged_attention_fallback_matches_contiguous():
    # the XLA gather fallback must agree bitwise with decode_attention over
    # each slot's contiguous view, and be invariant to garbage beyond length
    rng = np.random.default_rng(11)
    b, h, kv, hd, pn, ps, pps = 3, 4, 2, 8, 7, 4, 2
    span = pps * ps
    q = jnp.asarray(rng.standard_normal((b, 1, h, hd)).astype(np.float32))
    kp = jnp.asarray(rng.standard_normal((pn, ps, kv, hd)).astype(np.float32))
    vp = jnp.asarray(rng.standard_normal(kp.shape).astype(np.float32))
    pt = jnp.asarray([[1, 2], [3, 4], [5, 6]], jnp.int32)
    lengths = jnp.asarray([3, 8, 5], jnp.int32)
    out = paged_decode_attention(q, kp, vp, pt, lengths)
    idx = (np.asarray(pt)[:, :, None] * ps
           + np.arange(ps)[None, None, :]).reshape(b, span)
    kg = jnp.asarray(np.asarray(kp).reshape(pn * ps, kv, hd)[idx])
    vg = jnp.asarray(np.asarray(vp).reshape(pn * ps, kv, hd)[idx])
    ref = decode_attention(q, kg, vg, lengths)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # scribble over every position past each slot's length: masked entries
    # contribute exactly 0, so the output must not change by a single bit
    kp2, vp2 = np.asarray(kp).copy(), np.asarray(vp).copy()
    for i in range(b):
        for pos in range(int(lengths[i]), span):
            page, off = np.asarray(pt)[i, pos // ps], pos % ps
            kp2[page, off] = 1e6 * (i + 1)
            vp2[page, off] = -1e6
    out2 = paged_decode_attention(q, jnp.asarray(kp2), jnp.asarray(vp2),
                                  pt, lengths)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_batched_sample_matches_single_row():
    rng = np.random.default_rng(13)
    logits = jnp.asarray(rng.standard_normal((4, 128)).astype(np.float32))
    keys = jnp.stack([jax.random.key(s) for s in (7, 8, 9, 10)])
    steps = jnp.asarray([0, 3, 5, 2], jnp.int32)
    temps = jnp.asarray([0.0, 0.7, 1.3, 0.0], jnp.float32)
    got = np.asarray(_batched_sample(logits, keys, steps, temps))
    for i in range(4):
        want = _sample(logits[i:i + 1],
                       jax.random.fold_in(keys[i], steps[i]),
                       float(temps[i]))
        assert got[i] == int(np.asarray(want)[0])


# ---------------------------------------------------------------------------
# ServeFront integration
# ---------------------------------------------------------------------------


def test_drain_batched_front_matches_generate(params):
    from edgellm_tpu.serve import Request, ServeFront

    bat = ContinuousBatcher(CFG, params, BCFG)
    front = ServeFront(CFG, params, batcher=bat)
    reqs = [(_prompt(5, 20), 4, 0.0, 1), (_prompt(9, 21), 6, 0.8, 2),
            (_prompt(12, 22), 5, 0.0, 3)]
    for p, m, t, s in reqs:
        front.submit(Request(prompt_ids=p, max_new_tokens=m, temperature=t,
                             rng_seed=s))
    recs = front.drain_batched()
    assert len(recs) == 3
    by_prompt = {r.prompt_tokens: r for r in recs}
    for p, m, t, s in reqs:
        rec = by_prompt[len(p)]
        assert rec.outcome == "completed" and rec.backend == "batched"
        np.testing.assert_array_equal(rec.tokens[0],
                                      _solo(params, p, m, t, s))
    # the drain consumed the finished streams: nothing accumulates in the
    # batcher across drains on a long-lived server
    assert bat.results == {} and bat._streams == {}


def test_drain_batched_rejects_oversized_request_and_keeps_draining(params):
    from edgellm_tpu.serve import Request, ServeFront

    bat = ContinuousBatcher(CFG, params, BCFG)
    front = ServeFront(CFG, params, batcher=bat)
    good = (_prompt(5, 40), 4, 0.0, 7)
    front.submit(Request(prompt_ids=good[0], max_new_tokens=good[1],
                         temperature=good[2], rng_seed=good[3]))
    # prompt + granted tokens exceed the batcher's slot span (32): the drain
    # must record the rejection and keep serving the rest of the queue
    front.submit(Request(prompt_ids=_prompt(30, 41), max_new_tokens=8))
    recs = front.drain_batched()
    assert len(recs) == 2
    by_prompt = {r.prompt_tokens: r for r in recs}
    bad = by_prompt[30]
    assert bad.outcome == "rejected" and bad.reason == "exceeds_slot_span"
    ok = by_prompt[5]
    assert ok.outcome == "completed" and ok.backend == "batched"
    np.testing.assert_array_equal(
        ok.tokens[0], _solo(params, good[0], good[1], good[2], good[3]))
    assert bat.results == {} and bat._streams == {}


# ---------------------------------------------------------------------------
# split runtime: per-stage pools page the same way
# ---------------------------------------------------------------------------


def test_split_paged_decode_matches_generate_split(params):
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh
    from edgellm_tpu.serve.decode import generate_split

    mesh = make_stage_mesh(2)
    rt = SplitRuntime(CFG, SplitConfig(cuts=(2,),
                                       hop_codecs=("int8_per_token",)), mesh)
    placed = rt.place_params(params)
    streams = [dict(prompt=_prompt(5, 30), max_new=5, temp=0.0, seed=11),
               dict(prompt=_prompt(9, 31), max_new=4, temp=0.7, seed=22)]
    ref = [np.asarray(generate_split(
        rt, placed, jnp.asarray(s["prompt"])[None], s["max_new"],
        capacity=32, temperature=s["temp"],
        rng_key=jax.random.key(s["seed"])))[0] for s in streams]

    ps, npg, ms, pps = 8, 9, 4, 4
    host = PagedKVCache(CFG, num_pages=npg, page_size=ps, max_slots=ms,
                        pages_per_slot=pps, materialize=False)
    pool = rt.init_paged_pool(npg, ps)
    state = {}
    for i, s in enumerate(streams):
        n = len(s["prompt"])
        logits, cache = rt.prefill_decode(placed,
                                          jnp.asarray(s["prompt"])[None], 32)
        key = jax.random.key(s["seed"])
        tok0 = int(_sample(logits[:, -1], jax.random.fold_in(key, 0),
                           s["temp"])[0])
        slot = host.alloc_slot()
        host.ensure(slot, n)
        pool = rt.adopt_paged(pool, cache, 0, host._flat_indices(slot, n), n)
        host.lengths[slot] = n
        host.check_invariants()
        state[slot] = dict(i=i, key=key, toks=[tok0], **s)
    while any(len(v["toks"]) < v["max_new"] for v in state.values()):
        tok_ids = np.zeros((ms,), np.int32)
        active = []
        for slot, v in state.items():
            if len(v["toks"]) >= v["max_new"]:
                continue
            host.ensure(slot, int(host.lengths[slot]) + 1)
            tok_ids[slot] = v["toks"][-1]
            active.append(slot)
        pt, lens = host.device_tables()
        logits, pool = rt.decode_step_paged(placed, pool, pt, lens,
                                            jnp.asarray(tok_ids))
        for slot in active:
            v = state[slot]
            tok = int(_sample(logits[slot][None],
                              jax.random.fold_in(v["key"], len(v["toks"])),
                              v["temp"])[0])
            v["toks"].append(tok)
            host.lengths[slot] = int(host.lengths[slot]) + 1
    for v in state.values():
        np.testing.assert_array_equal(np.asarray(v["toks"], np.int32),
                                      ref[v["i"]])
