"""Wire-codec tests: bit-exact pack/unpack round-trips, parity with the simulate
codecs (the wire codec must reproduce the reference's simulated quantization
exactly while producing real packed bytes), and measured byte accounting against
the analytic table in BASELINE.md.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.codecs.packing import (
    pack_int4, unpack_int4, pack_ternary, unpack_ternary,
    get_wire_codec, WIRE_CODECS,
)
from edgellm_tpu.codecs import (
    int4_token_select, per_token_affine_int8, channel_wise_quant,
)


@pytest.fixture
def hidden(rng):
    return jnp.asarray(rng.normal(size=(2, 16, 24)).astype(np.float32))


def test_int4_pack_roundtrip_exact(rng):
    codes = jnp.asarray(rng.integers(-8, 8, size=(3, 5, 32), dtype=np.int64).astype(np.int8))
    packed = pack_int4(codes)
    assert packed.dtype == jnp.uint8 and packed.shape == (3, 5, 16)
    np.testing.assert_array_equal(np.asarray(unpack_int4(packed)), np.asarray(codes))


def test_ternary_pack_roundtrip_exact(rng):
    codes = jnp.asarray(rng.integers(-1, 2, size=(2, 7, 16), dtype=np.int64).astype(np.int8))
    packed = pack_ternary(codes)
    assert packed.dtype == jnp.uint8 and packed.shape == (2, 7, 4)
    np.testing.assert_array_equal(np.asarray(unpack_ternary(packed)), np.asarray(codes))


@pytest.mark.parametrize("name", WIRE_CODECS)
def test_decode_encode_is_finite_and_close(hidden, name):
    codec = get_wire_codec(name)
    out = codec.decode(codec.encode(hidden))
    assert out.shape == hidden.shape
    assert np.isfinite(np.asarray(out)).all()
    # even ternary should stay within a broad band of the input
    assert float(jnp.max(jnp.abs(out - hidden))) < 10.0


def test_int4_global_matches_simulate(hidden):
    """Wire int4_global == simulate int4 with every token selected."""
    codec = get_wire_codec("int4_global")
    wire = codec.decode(codec.encode(hidden))
    sim = int4_token_select(hidden, jnp.arange(hidden.shape[1], 0.0, -1.0), 1.0)
    np.testing.assert_array_equal(np.asarray(wire), np.asarray(sim))


def test_int8_per_token_matches_simulate(hidden):
    codec = get_wire_codec("int8_per_token")
    wire = codec.decode(codec.encode(hidden))
    sim = per_token_affine_int8(hidden)
    np.testing.assert_array_equal(np.asarray(wire), np.asarray(sim))


def test_int8_per_token_constant_token_passthrough():
    h = jnp.full((1, 4, 8), 0.4)
    codec = get_wire_codec("int8_per_token")
    np.testing.assert_allclose(np.asarray(codec.decode(codec.encode(h))), 0.4, atol=1e-7)


@pytest.mark.parametrize("wire,channel", [
    ("int8_per_channel", "channel_8"),
    ("int4_per_channel", "channel_4"),
    ("ternary_mean", "channel_1_mean"),
    ("ternary_max", "channel_1_max"),
])
def test_per_channel_wire_matches_simulate(hidden, wire, channel):
    codec = get_wire_codec(wire)
    got = codec.decode(codec.encode(hidden))
    want = channel_wise_quant(hidden, channel)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_payload_bytes_match_baseline_table():
    """BASELINE.md analytic boundary payloads, now measured: Qwen d=896 ->
    fp16 1792 B/tok, int8 896+scales, int4 448+scales, ternary 224+scales."""
    S, D = 512, 896
    per_tok = lambda name: get_wire_codec(name).payload_bytes((1, S, D)) / S
    assert per_tok("fp16") == 1792
    assert per_tok("fp32") == 3584
    q8 = per_tok("int8_per_token")
    assert 896 <= q8 <= 896 + 16  # + 2 fp32 scalars/token
    q4 = per_tok("int4_per_token")
    assert 448 <= q4 <= 448 + 8
    t = per_tok("ternary_max")
    assert 224 <= t <= 224 + 8  # + D fp32 channel scales amortized over S
    ch8 = per_tok("int8_per_channel")
    assert 896 <= ch8 <= 896 + 8


def test_codecs_jit_and_shapes_static(hidden):
    for name in WIRE_CODECS:
        codec = get_wire_codec(name)
        f = jax.jit(lambda h, c=codec: c.decode(c.encode(h)))
        out = f(hidden)
        assert out.shape == hidden.shape
