"""graphlint test coverage: every AST rule catches its seeded fixture, every
graph-contract checker catches its known-bad jaxpr, clean code passes, and
the CLI's exit code reflects both.

The AST fixtures live in ``tests/graphlint_fixtures/`` and are PARSED, never
imported. The known-bad graphs are built here at test time (extra
collective, f64 leak, missing donation, wrong wire dtype/bytes, host
callback, non-identical disabled-config graph).
"""
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from edgellm_tpu.lint.ast_rules import lint_file, lint_source
from edgellm_tpu.lint.contracts import (GRAPH_CONTRACTS, GraphContract,
                                        check_identity, check_traced,
                                        count_collectives,
                                        donated_input_count,
                                        graph_fingerprint, ppermute_traffic)
from edgellm_tpu.parallel.split import make_stage_mesh
from edgellm_tpu.utils.jax_compat import shard_map

FIXTURES = os.path.join(os.path.dirname(__file__), "graphlint_fixtures")


def _fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def _repo_root():
    import pathlib

    return pathlib.Path(__file__).resolve().parent.parent


def _rules(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# layer 1: each AST rule catches its seeded fixture
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fixture,rule,min_hits", [
    ("bad_eg001.py", "EG001", 3),  # if / while / assert on traced values
    ("bad_eg002.py", "EG002", 2),  # time.time + print reachable from jit
    ("bad_eg003.py", "EG003", 1),  # np.sqrt on a tracer
    ("bad_eg004.py", "EG004", 2),  # jit call + partial-decorated, cfg unstatic
    ("bad_eg005.py", "EG005", 2),  # int(...) + .item() in a generate loop
    ("bad_eg006.py", "EG006", 2),  # captured list append + dict store
])
def test_ast_rule_catches_fixture(fixture, rule, min_hits):
    findings = lint_file(_fixture(fixture))
    hits = [f for f in findings if f.rule == rule]
    assert len(hits) >= min_hits, \
        f"{fixture}: expected >= {min_hits} {rule} findings, got {findings}"
    assert all(f.line > 0 for f in hits)  # every finding is line-anchored


def test_clean_fixture_passes():
    assert lint_file(_fixture("clean.py")) == []


def test_real_package_ast_clean():
    """The shipped package must lint clean — the CI gate depends on it."""
    from edgellm_tpu.lint.ast_rules import iter_package_files, lint_paths

    import edgellm_tpu

    pkg_root = os.path.dirname(os.path.abspath(edgellm_tpu.__file__))
    findings = lint_paths(iter_package_files(pkg_root))
    assert findings == [], [f.format() for f in findings]


def test_suppression_comment_disables_rule():
    src = (
        "import jax\nimport jax.numpy as jnp\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if jnp.any(x > 0):  # graphlint: disable=EG001\n"
        "        return x + 1\n"
        "    return x\n")
    assert lint_source(src, "t.py") == []
    # ...but an unrelated rule id does not suppress it
    src_wrong = src.replace("disable=EG001", "disable=EG002")
    assert _rules(lint_source(src_wrong, "t.py")) == {"EG001"}


def test_suppression_comment_multi_rule():
    """Comma-separated disables silence every listed rule and nothing else."""
    src = (
        "import jax\nimport jax.numpy as jnp\nimport numpy as np\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.sqrt(x)  # graphlint: disable={rules}\n")
    # the fired rule is listed (alongside another): suppressed
    assert lint_source(src.format(rules="EG001,EG003"), "t.py") == []
    # listed rules don't include the fired one: still flagged
    assert _rules(lint_source(src.format(rules="EG001,EG002"), "t.py")) \
        == {"EG003"}
    # whitespace around the comma is tolerated
    assert lint_source(src.format(rules="EG003, EG001"), "t.py") == []


def test_collect_suppressions_inventory(tmp_path):
    from edgellm_tpu.lint.ast_rules import collect_suppressions

    p = tmp_path / "mod.py"
    p.write_text(
        "x = 1  # graphlint: disable=EG001,EG003\n"
        "y = 2\n"
        "z = 3  # graphlint: disable\n")
    marks = collect_suppressions([str(p)])
    assert marks == [(str(p), 1, {"EG001", "EG003"}), (str(p), 3, None)]


def test_unreachable_code_not_flagged():
    """Host-only modules may branch on arrays / print / use numpy freely —
    the rules only fire on jit-reachable functions."""
    src = (
        "import numpy as np\n\n"
        "def host(x):\n"
        "    print('fine')\n"
        "    return np.sqrt(x)\n")
    assert lint_source(src, "t.py") == []


# ---------------------------------------------------------------------------
# layer 2: each graph-contract checker catches its known-bad jaxpr
# ---------------------------------------------------------------------------


def _shmap(body, n_out_stage=False):
    mesh = make_stage_mesh(2)
    return shard_map(body, mesh=mesh, in_specs=(P("stage"),),
                     out_specs=P("stage") if n_out_stage else P(),
                     check_vma=False)


def test_extra_collective_caught():
    """A silently-added psum trips the declared collective count."""

    def one_psum(x):
        return jax.lax.psum(x, "stage")

    def two_psums(x):
        return jax.lax.psum(jax.lax.psum(x, "stage"), "stage")

    x = jnp.ones((2, 4), jnp.float32)
    contract = GraphContract(name="t.collectives",
                             collectives={"psum": 1}, forbid=())
    assert check_traced(contract, _shmap(one_psum), (x,)) == []
    bad = check_traced(contract, _shmap(two_psums), (x,))
    assert _rules(bad) == {"GC-collectives"}


def test_f64_leak_caught():
    contract = GraphContract(name="t.f64", forbid=("f64",))

    def promotes(x):
        return x.astype(jnp.float64) * 2.0

    x = jnp.ones((4,), jnp.float32)
    with jax.experimental.enable_x64():
        bad = check_traced(contract, promotes, (x,))
    assert _rules(bad) == {"GC-f64"}
    assert check_traced(contract, lambda y: y * 2.0, (x,)) == []


def test_host_callback_caught():
    contract = GraphContract(name="t.cb", forbid=("host_callback",))

    def with_debug(x):
        jax.debug.print("x={x}", x=x)
        return x + 1

    x = jnp.ones((4,), jnp.float32)
    bad = check_traced(contract, with_debug, (x,))
    assert _rules(bad) == {"GC-callback"}
    assert check_traced(contract, lambda y: y + 1, (x,)) == []


def test_missing_donation_caught():
    contract = GraphContract(name="t.donate", forbid=(), donate=1)
    x = jnp.ones((8,), jnp.float32)

    undonated = jax.jit(lambda c: c + 1)
    bad = check_traced(contract, undonated, (x,),
                       lowerable=undonated, lower_args=(x,))
    assert _rules(bad) == {"GC-donate"}

    donated = jax.jit(lambda c: c + 1, donate_argnums=(0,))
    assert check_traced(contract, donated, (x,),
                        lowerable=donated, lower_args=(x,)) == []
    assert donated_input_count(donated, x) >= 1
    assert donated_input_count(undonated, x) == 0


def test_wire_dtype_and_bytes_caught():
    """f32 crossing a hop that declares an int8 wire, and a payload that
    drifted from the declared byte width, are both flagged."""

    def hop_f32(x):
        return jax.lax.ppermute(x, "stage", [(0, 1)])

    fn = _shmap(hop_f32, n_out_stage=True)
    x = jnp.ones((2, 8), jnp.float32)  # local (1, 8) f32 = 32 wire bytes

    contract = GraphContract(name="t.wire", forbid=(),
                             wire_dtypes=frozenset({"int8"}),
                             wire_bytes=32)
    bad = check_traced(contract, fn, (x,))
    assert _rules(bad) == {"GC-wire-dtype"}

    contract2 = GraphContract(name="t.wire2", forbid=(),
                              wire_dtypes=frozenset({"float32"}),
                              wire_bytes=16)
    bad2 = check_traced(contract2, fn, (x,))
    assert _rules(bad2) == {"GC-wire-bytes"}

    good = GraphContract(name="t.wire3", forbid=(),
                         wire_dtypes=frozenset({"float32"}), wire_bytes=32)
    assert check_traced(good, fn, (x,)) == []
    traffic = ppermute_traffic(jax.make_jaxpr(fn)(x))
    assert traffic == [("float32", (1, 8), 32)]


def test_collective_count_recurses_into_scan():
    """Counts are static graph counts: a ppermute inside a scan body counts
    once, however many trip iterations run."""

    def body(x):
        def step(h, _):
            return jax.lax.ppermute(h, "stage", [(0, 1)]), None

        out, _ = jax.lax.scan(step, x, None, length=5)
        return out

    fn = _shmap(body, n_out_stage=True)
    jaxpr = jax.make_jaxpr(fn)(jnp.ones((2, 4), jnp.float32))
    assert count_collectives(jaxpr) == {"ppermute": 1}


def test_identity_checker_flags_divergent_graphs():
    x = jnp.ones((4,), jnp.float32)
    f = lambda a: a * 2.0  # noqa: E731
    g = lambda a: a * 2.0 + 1.0  # noqa: E731
    assert check_identity("t.same", f, (x,), f, (x,)) == []
    bad = check_identity("t.diff", f, (x,), g, (x,))
    assert _rules(bad) == {"GC-identity"}
    assert graph_fingerprint(f, x) != graph_fingerprint(g, x)


def test_production_contracts_registered():
    """Importing the stack registers every declared contract — the CLI's
    graph layer fails loudly if one goes missing."""
    import edgellm_tpu.codecs.faults  # noqa: F401
    import edgellm_tpu.models.transformer  # noqa: F401
    import edgellm_tpu.parallel.split  # noqa: F401
    import edgellm_tpu.serve.decode  # noqa: F401

    expected = {"transformer.prefill", "transformer.decode_step",
                "decode.prefill", "decode.step", "split.forward",
                "split.decode_step", "faults.hop"}
    assert expected <= set(GRAPH_CONTRACTS)
    # the decorator is zero-cost: the functions stay plain functions
    assert GRAPH_CONTRACTS["transformer.prefill"].fn.__name__ == "prefill"


# ---------------------------------------------------------------------------
# CLI exit codes
# ---------------------------------------------------------------------------


def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "edgellm_tpu.lint", *args],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_cli_nonzero_on_seeded_violations(tmp_path):
    bad = [_fixture(f"bad_eg00{i}.py") for i in range(1, 7)]
    report_path = tmp_path / "report.json"
    proc = _run_cli("--ast-only", "--json", str(report_path), *bad)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    import json

    report = json.loads(report_path.read_text())
    assert not report["ok"]
    assert {f["rule"] for f in report["findings"]} == {
        "EG001", "EG002", "EG003", "EG004", "EG005", "EG006"}


def test_cli_zero_on_clean_paths():
    proc = _run_cli("--ast-only", _fixture("clean.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_thread_only_on_seeded_fixtures():
    bad = [_fixture(f"bad_eg10{i}.py") for i in range(1, 5)]
    proc = _run_cli("--thread-only", *bad)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    for rule in ("EG101", "EG102", "EG103", "EG104"):
        assert rule in proc.stdout, (rule, proc.stdout)


def test_cli_show_suppressed_lists_markers():
    proc = _run_cli("--thread-only", "--show-suppressed",
                    _fixture("clean.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "suppressions: 0 marker(s)" in proc.stdout
    # with a real package walk the audit lists file:line for every marker
    proc = _run_cli("--thread-only", "--show-suppressed")
    assert "suppressions:" in proc.stdout, proc.stdout


def test_cli_sarif_on_violations(tmp_path):
    import json

    sarif_path = tmp_path / "out.sarif"
    proc = _run_cli("--thread-only", "--sarif", str(sarif_path),
                    _fixture("bad_eg102.py"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(sarif_path.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "graphlint"
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == {"EG102"}
    results = run["results"]
    assert results and all(r["ruleId"] == "EG102" for r in results)
    region = results[0]["locations"][0]["physicalLocation"]["region"]
    assert region["startLine"] > 0


def test_cli_sarif_on_clean_paths(tmp_path):
    import json

    sarif_path = tmp_path / "clean.sarif"
    proc = _run_cli("--ast-only", "--sarif", str(sarif_path),
                    _fixture("clean.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(sarif_path.read_text())
    assert doc["runs"][0]["results"] == []


def test_cli_json_report_unchanged_shape(tmp_path):
    """--json stays byte-compatible: same four keys, same ordering."""
    import json

    report_path = tmp_path / "r.json"
    proc = _run_cli("--ast-only", "--json", str(report_path),
                    _fixture("clean.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    text = report_path.read_text()
    report = json.loads(text)
    assert list(report) == sorted(report)  # sort_keys=True preserved
    assert set(report) == {"ok", "findings", "checked_contracts", "skipped"}
    assert text == json.dumps(report, indent=2, sort_keys=True) + "\n"


@pytest.mark.slow
def test_cli_zero_on_real_package(tmp_path):
    """Acceptance: the full CLI (AST + graph contracts + config lattice)
    exits 0 on the real package. Slow — it traces every entry point and
    AOT-lowers every config; CI's graphlint/latticelint jobs run it as the
    required gate."""
    report_path = tmp_path / "report.json"
    matrix_path = tmp_path / "capability_matrix.json"
    proc = _run_cli("--no-mypy", "--json", str(report_path),
                    "--matrix", str(matrix_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json

    report = json.loads(report_path.read_text())
    assert report["ok"] and len(report["checked_contracts"]) >= 8
    # the lattice layer ran and covered every shipped config
    lattice = [c for c in report["checked_contracts"]
               if c.startswith("lattice.config:")]
    n_configs = len(list((_repo_root() / "configs").glob("*.json")))
    assert len(lattice) == n_configs
    assert "lattice.pairwise-compat" in report["checked_contracts"]
    matrix = json.loads(matrix_path.read_text())
    assert len(matrix["configs"]) == n_configs
