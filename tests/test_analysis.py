"""Analysis-module tests: JS/KL formulas vs the notebook's definitions, and the
pairwise layer-distance pipeline on a tiny model."""
import numpy as np

import jax

from edgellm_tpu.models import tiny_config, init_params
from edgellm_tpu.analysis import (
    kl_divergence,
    jensen_shannon_divergence,
    layer_importance_distributions,
    pairwise_layer_distances,
)

CFG = tiny_config("gpt_neox", num_layers=4, hidden_size=32, num_heads=4, vocab_size=128)


def test_kl_matches_notebook_formula():
    p = np.array([0.5, 0.5, 0.0])
    q = np.array([0.25, 0.5, 0.25])
    want = 0.5 * np.log2(0.5 / 0.25)  # zero-p term guarded out
    np.testing.assert_allclose(kl_divergence(p, q), want, rtol=1e-12)
    assert kl_divergence(p, p) == 0.0


def test_js_symmetric_and_bounded(rng):
    p = rng.random(16); p /= p.sum()
    q = rng.random(16); q /= q.sum()
    js_pq, js_qp = jensen_shannon_divergence(p, q), jensen_shannon_divergence(q, p)
    np.testing.assert_allclose(js_pq, js_qp, rtol=1e-12)
    assert 0.0 <= js_pq <= 1.0  # base-2 JS divergence is bounded by 1
    assert jensen_shannon_divergence(p, p) < 1e-12


def test_pairwise_layer_distances_pipeline(rng):
    params = init_params(CFG, jax.random.key(3))
    samples = [rng.integers(0, CFG.vocab_size, n) for n in (20, 28, 20)]
    dists = layer_importance_distributions(CFG, params, samples)
    assert len(dists) == CFG.num_layers and len(dists[0]) == 3
    # importance distributions sum to 1 over positions (attention mass)
    for layer in dists:
        for d in layer:
            np.testing.assert_allclose(d.sum(), 1.0, atol=1e-5)
    mat = pairwise_layer_distances(dists)
    assert mat.shape == (4, 4)
    upper = [(i, j) for i in range(4) for j in range(i + 1, 4)]
    assert all(np.isfinite(mat[i, j]) for i, j in upper)
    assert all(np.isnan(mat[j, i]) for i, j in upper)
    assert np.isnan(np.diag(mat)).all()


def test_bucket_lengths_bounds_compiles():
    from edgellm_tpu.analysis import bucket_lengths

    lengths = list(range(17, 117))  # 100 distinct ragged lengths
    buckets = bucket_lengths(lengths, 4)
    assert len(buckets) <= 4 and buckets == sorted(buckets)
    assert buckets[0] == 17 and buckets[-1] == 116  # extremes covered
    # few distinct lengths pass through untouched
    assert bucket_lengths([8, 8, 16], 4) == [8, 16]


def test_ragged_corpus_compiles_at_most_max_compiles(rng):
    """100 ragged samples run with <= 4 distinct stats-forward shapes (the
    clipped lengths), verified by counting actual jit cache misses."""
    from edgellm_tpu.analysis.distances import _per_layer_importance

    params = init_params(CFG, jax.random.key(3))
    samples = [rng.integers(0, CFG.vocab_size, n)
               for n in rng.integers(16, 116, size=100)]
    _per_layer_importance.cache_clear()
    fn = _per_layer_importance(CFG)
    dists = layer_importance_distributions(CFG, params, samples, max_compiles=4)
    assert len(dists[0]) == 100
    assert fn._cache_size() <= 4
    # every clipped sample still yields a normalized distribution
    for d in dists[0]:
        np.testing.assert_allclose(d.sum(), 1.0, atol=1e-5)


def test_heatmap_artifact(tmp_path, rng):
    from edgellm_tpu.analysis import save_heatmap

    mat = np.full((4, 4), np.nan)
    mat[np.triu_indices(4, 1)] = rng.random(6)
    path = tmp_path / "heat.png"
    save_heatmap(mat, str(path))
    assert path.exists() and path.stat().st_size > 1000
