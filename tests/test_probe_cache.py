"""The probe-derived substitution policy (codecs/probe_cache.py): cache
hit / miss / fallback, and how pallas_variant's measured_wins_only gate
consumes it. VERDICT r4 weak #2: the policy must come from measurement on
THIS chip, with the frozen constant only as the no-data fallback."""
import json

import pytest

from edgellm_tpu.codecs import probe_cache
from edgellm_tpu.codecs.pallas_kernels import (PALLAS_DEFAULT_WINS,
                                               default_substituted,
                                               pallas_variant)
from edgellm_tpu.codecs.packing import get_wire_codec, selective_int4


@pytest.fixture
def cache(tmp_path, monkeypatch):
    path = tmp_path / "wins.json"
    monkeypatch.setenv("EDGELLM_PROBE_CACHE", str(path))
    return path


def _probe_rows(**speedups):
    return [{"codec": k, "roundtrip_speedup_vs_jnp": v}
            for k, v in speedups.items()]


def test_record_and_load_roundtrip(cache):
    assert probe_cache.load_speedups() is None  # miss: no file yet
    wrote = probe_cache.record(_probe_rows(int4_per_token=1.33,
                                           int8_per_token=0.79))
    assert wrote == str(cache)
    got = probe_cache.load_speedups()
    assert got == {"int4_per_token": 1.33, "int8_per_token": 0.79}
    # merge, not replace: a later run updates one codec, keeps the rest
    probe_cache.record(_probe_rows(int8_per_token=1.1))
    got = probe_cache.load_speedups()
    assert got == {"int4_per_token": 1.33, "int8_per_token": 1.1}


def test_fingerprint_keys_are_isolated(cache):
    probe_cache.record(_probe_rows(int4_per_token=1.5), fp="tpu:TPU v99")
    # current (cpu) fingerprint has no data -> miss
    assert probe_cache.load_speedups() is None
    assert probe_cache.load_speedups("tpu:TPU v99") == {"int4_per_token": 1.5}


def test_measured_win_hit_miss(cache):
    assert probe_cache.measured_win("int4_per_token") is None  # no data
    probe_cache.record(_probe_rows(int4_per_token=1.33, int8_per_token=0.79))
    assert probe_cache.measured_win("int4_per_token") is True
    assert probe_cache.measured_win("int8_per_token") is False
    assert probe_cache.measured_win("ternary_mean") is None  # unprobed codec
    # the selective family maps onto one policy key
    probe_cache.record(_probe_rows(**{"selective_int4_r0.5_bf16": 1.2}))
    assert probe_cache.measured_win("selective_int4_r0.25_bf16") is True
    # break-even readings do NOT flap a codec into the default path: the win
    # must clear WIN_MARGIN, not 1.0
    probe_cache.record(_probe_rows(int8_per_channel=1.02))
    assert probe_cache.measured_win("int8_per_channel") is False


def test_record_prefers_unrounded_ratio(cache):
    """ADVICE r5 #3: a 1.046x reading display-rounds to 1.05 — WIN_MARGIN
    must see the raw ratio, or the rounding manufactures a win."""
    probe_cache.record([{"codec": "int8_per_token",
                         "roundtrip_speedup_vs_jnp": 1.05,
                         "roundtrip_speedup_vs_jnp_raw": 1.046}])
    assert probe_cache.load_speedups() == {"int8_per_token": 1.046}
    assert probe_cache.measured_win("int8_per_token") is False
    # rows without the raw field (older probe output) still load
    probe_cache.record(_probe_rows(int4_per_token=1.33))
    assert probe_cache.measured_win("int4_per_token") is True


def test_no_data_falls_back_to_frozen_set(cache):
    for base in ("int4_per_token", "int8_per_token", "selective_int4"):
        assert default_substituted(base) == (base in PALLAS_DEFAULT_WINS)


def test_corrupt_cache_degrades_to_fallback(cache):
    cache.write_text("{not json")
    assert probe_cache.load_speedups() is None
    assert default_substituted("int4_per_token")  # fallback set decides
    # and record() recovers the file
    probe_cache.record(_probe_rows(int4_per_token=1.2))
    assert probe_cache.load_speedups() == {"int4_per_token": 1.2}
    json.loads(cache.read_text())  # valid JSON again


def test_pallas_variant_consults_cache_over_constant(cache):
    int4 = get_wire_codec("int4_per_token")
    # no data: the frozen fallback substitutes int4_per_token
    assert pallas_variant(int4, measured_wins_only=True) is not None
    # a measured LOSS on this chip overrides the constant (the r03->r04
    # int8_per_token 2.12x -> 0.79x flip can never silently ship again)
    probe_cache.record(_probe_rows(int4_per_token=0.8))
    assert pallas_variant(int4, measured_wins_only=True) is None
    # a measured WIN enables a codec the constant excludes
    probe_cache.record(_probe_rows(int8_per_token=1.2))
    got = pallas_variant(get_wire_codec("int8_per_token"),
                         measured_wins_only=True)
    assert got is not None and got.name.endswith("_pallas")
    # explicit *_pallas pins are honored regardless of the cache
    pinned = pallas_variant(got, measured_wins_only=True)
    assert pinned is got
    # the selective codec can never be substituted — its twin was DELETED on
    # measurement, and even a (stale) cache win cannot resurrect it
    sel = selective_int4(0.25, "bf16")
    probe_cache.record(_probe_rows(**{"selective_int4_r0.5_bf16": 1.15}))
    assert pallas_variant(sel, measured_wins_only=True) is None
    assert pallas_variant(sel) is None
