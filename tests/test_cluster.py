"""Cluster-scale replica router (PR 17): prefix-affinity placement,
per-replica failure isolation, and the chaos soak.

Correctness anchors, in order of importance:

- a mid-soak replica kill loses ZERO accepted requests: queued work
  re-admits from scratch and mid-flight work resumes from its
  DecodeCheckpoint, and every completed request is TOKEN-IDENTICAL to the
  fault-free single-replica reference — at greedy AND at temperature > 0
  via its recorded seed;
- placement is deterministic: longest-shared-prefix affinity above the
  threshold, least-loaded fallback, (queue_depth, id) tiebreak;
- a dead replica respawns from a clean plan after exponential backoff with
  seeded jitter on the injected clock, and rejoins the rotation only after
  its half-open probe requests complete (a failed probe re-kills it);
- exactly ONE flight-recorder post-mortem per induced failure;
- the simulated autoscaler obeys min-dwell hysteresis — pressure swings
  inside the dwell window cannot flap the fleet;
- fleet capacity scales with N: the discrete-event replicas serve in
  parallel on the shared virtual timeline.
"""
import os

import numpy as np
import pytest

from edgellm_tpu.serve import Request
from edgellm_tpu.serve.cluster import (AutoscalerConfig, ClusterConfig,
                                       ClusterConfigError, ClusterFront,
                                       RespawnConfig, SimReplicaConfig,
                                       SimReplicaFront, drive_cluster,
                                       sim_reference_tokens)
from edgellm_tpu.serve.soak import ClusterSoakConfig, run_cluster_soak
from edgellm_tpu.utils.clock import FakeClock


def _prompt(seed, n=16, vocab=50_000):
    rng = np.random.default_rng(seed)
    return rng.integers(1, vocab, size=n).astype(np.int32)


def _fleet(n=2, clock=None, sim_cfg=None, **cfg_kw):
    clock = clock if clock is not None else FakeClock()
    scfg = sim_cfg if sim_cfg is not None else SimReplicaConfig()
    fronts = {}

    def factory(rid, gen):
        f = SimReplicaFront(scfg, clock=clock, replica_id=rid)
        fronts[(rid, gen)] = f
        return f

    cluster = ClusterFront(factory, ClusterConfig(num_replicas=n, **cfg_kw),
                           clock=clock)
    return cluster, clock, fronts


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


def test_config_validation_rejects_bad_fields():
    with pytest.raises(ClusterConfigError):
        ClusterConfig(num_replicas=0)
    with pytest.raises(ClusterConfigError):
        ClusterConfig(max_readmissions=-1)
    with pytest.raises(ClusterConfigError):
        RespawnConfig(backoff_factor=0.5)
    with pytest.raises(ClusterConfigError):
        RespawnConfig(half_open_probes=0)
    with pytest.raises(ClusterConfigError):
        AutoscalerConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ClusterConfigError):
        ClusterConfig(respawn={"backoff_base_s": 1.0})  # dict, not config


# ---------------------------------------------------------------------------
# placement: affinity, least-loaded fallback, deterministic tiebreak
# ---------------------------------------------------------------------------


def test_affinity_routes_to_the_warm_replica():
    cluster, clock, _ = _fleet(2, min_affinity_tokens=4)
    shared = _prompt(7, n=16)
    first = cluster.submit(Request(prompt_ids=shared, max_new_tokens=4))
    recs = drive_cluster(cluster, clock)
    assert [r.request_id for r in recs] == [first]
    warm_replica = recs[0].plan["replica"]
    # same 16-token prefix + fresh suffix: affinity must beat least-loaded
    # even though both replicas are idle
    follow = np.concatenate([shared, _prompt(8, n=8)]).astype(np.int32)
    cluster.submit(Request(prompt_ids=follow, max_new_tokens=4))
    recs = drive_cluster(cluster, clock)
    assert recs[0].plan["replica"] == warm_replica
    assert cluster.totals["affinity"] == 1


def test_least_loaded_fallback_with_deterministic_tiebreak():
    cluster, clock, _ = _fleet(3, min_affinity_tokens=4)
    # all idle, nothing indexed: equal depth -> lowest id, then the queue
    # depths break the next ties
    crids = [cluster.submit(Request(prompt_ids=_prompt(i), max_new_tokens=4))
             for i in range(3)]
    placed = {crid: cluster._placements[crid].replica_id for crid in crids}
    assert [placed[c] for c in crids] == [0, 1, 2]
    assert cluster.totals["least_loaded"] == 3
    recs = drive_cluster(cluster, clock)
    assert len(recs) == 3


def test_short_shared_prefix_does_not_trigger_affinity():
    cluster, clock, _ = _fleet(2, min_affinity_tokens=8,
                               sim_cfg=SimReplicaConfig(prefix_block=4))
    p = _prompt(3, n=16)
    cluster.submit(Request(prompt_ids=p, max_new_tokens=4))
    drive_cluster(cluster, clock)
    # only the first 4 tokens shared (< min_affinity_tokens=8)
    follow = np.concatenate([p[:4], _prompt(9, n=12)]).astype(np.int32)
    cluster.submit(Request(prompt_ids=follow, max_new_tokens=4))
    drive_cluster(cluster, clock)
    assert cluster.totals["affinity"] == 0
    assert cluster.totals["least_loaded"] == 2


# ---------------------------------------------------------------------------
# fleet capacity scales with N (the DES property the goodput gate measures)
# ---------------------------------------------------------------------------


def test_two_replicas_finish_in_about_half_the_virtual_time():
    def span(n_replicas):
        cluster, clock, _ = _fleet(n_replicas)
        t0 = clock.now
        for i in range(8):
            cluster.submit(Request(prompt_ids=_prompt(i), max_new_tokens=16))
        recs = drive_cluster(cluster, clock)
        assert len(recs) == 8
        return clock.now - t0

    one, two = span(1), span(2)
    assert two < 0.75 * one, (one, two)


# ---------------------------------------------------------------------------
# replica kill: zero accepted loss, token identity at greedy AND sampled
# ---------------------------------------------------------------------------


def _kill_workload(n=12):
    """Half greedy, half sampled with a recorded per-request seed."""
    reqs = []
    for i in range(n):
        sampled = i % 2 == 1
        reqs.append(Request(prompt_ids=_prompt(100 + i),
                            max_new_tokens=16,
                            temperature=0.7 if sampled else 0.0,
                            rng_seed=1000 + i if sampled else 0))
    return reqs


def _reference_tokens(req):
    """The fault-free single-replica reference: a 1-replica fleet with no
    chaos serves the same request; its tokens are the identity target."""
    cluster, clock, _ = _fleet(1)
    cluster.submit(req)
    recs = drive_cluster(cluster, clock)
    assert len(recs) == 1 and recs[0].outcome == "completed"
    return np.asarray(recs[0].tokens).reshape(-1)


def test_replica_kill_token_identity_greedy_and_sampled(tmp_path):
    reqs = _kill_workload(12)
    cluster, clock, _ = _fleet(
        2, checkpoint_dir=str(tmp_path / "ckpt"),
        flight_dir=str(tmp_path / "flight"))
    crid_to_req = {cluster.submit(r): r for r in reqs}
    # run partway so replica 0 is mid-decode, then kill it
    partial = cluster.drain(max_requests=3)
    nxt = cluster.next_event_s()
    while len(partial) < 3:
        if nxt is not None and nxt > clock.now:
            clock.set_time(nxt)
        partial.extend(cluster.drain(max_requests=3 - len(partial)))
        nxt = cluster.next_event_s()
    cluster.kill_replica(0, "chaos")
    records = partial + drive_cluster(cluster, clock)
    # zero accepted loss: every submitted request reached exactly one
    # terminal record, all completed
    assert sorted(r.request_id for r in records) == sorted(crid_to_req)
    assert all(r.outcome == "completed" for r in records)
    # token identity vs the fault-free single-replica reference, greedy and
    # sampled alike (the recorded seed pins the sampled stream)
    for rec in records:
        ref = _reference_tokens(crid_to_req[rec.request_id])
        assert np.array_equal(np.asarray(rec.tokens).reshape(-1), ref), \
            f"request {rec.request_id} diverged after the kill"
    assert cluster.totals["readmitted"] > 0
    assert len(cluster.kills) == 1


def test_mid_flight_checkpoint_resume_has_zero_recompute():
    cluster, clock, fronts = _fleet(2)
    req = Request(prompt_ids=_prompt(42), max_new_tokens=16)
    cluster.submit(req)
    # advance through prefill + one decode chunk so tokens exist mid-flight
    for _ in range(8):
        if not cluster.drain():
            nxt = cluster.next_event_s()
            if nxt is None:
                break
            clock.set_time(nxt)
        if fronts[(0, 0)]._current is not None \
                and fronts[(0, 0)]._current.tokens:
            break
    assert fronts[(0, 0)]._current is not None
    done_before = len(fronts[(0, 0)]._current.tokens)
    assert 0 < done_before < 16
    cluster.kill_replica(0, "chaos")
    recs = drive_cluster(cluster, clock)
    assert len(recs) == 1 and recs[0].outcome == "completed"
    assert np.array_equal(
        np.asarray(recs[0].tokens).reshape(-1),
        sim_reference_tokens(np.asarray(req.prompt_ids), 16)[0])
    # the checkpointed chain resumed where it stopped — nothing recomputed
    assert cluster.totals["recompute_tokens"] == 0
    assert recs[0].recovery["readmissions"] == 1


# ---------------------------------------------------------------------------
# respawn: exponential backoff + jitter, half-open probes
# ---------------------------------------------------------------------------


def test_respawn_backoff_grows_and_half_open_probes_gate_rejoin():
    rs = RespawnConfig(backoff_base_s=1.0, backoff_factor=2.0,
                       backoff_max_s=30.0, jitter_frac=0.25,
                       half_open_probes=2)
    cluster, clock, fronts = _fleet(2, respawn=rs)
    cluster.kill_replica(0, "chaos")
    r0 = cluster.replicas[0]
    first_backoff = r0.respawn_at - clock.now
    assert 1.0 <= first_backoff <= 1.0 * 1.25
    # not due yet: the replica stays dead
    clock.advance(first_backoff / 2)
    cluster.submit(Request(prompt_ids=_prompt(1), max_new_tokens=4))
    assert r0.state == "dead"
    clock.advance(first_backoff)  # past respawn_at
    cluster.submit(Request(prompt_ids=_prompt(2), max_new_tokens=4))
    assert r0.state == "probing"
    assert r0.generation == 1  # clean plan: a NEW front from the factory
    assert (0, 1) in fronts
    # probing replicas take placements first (they need live traffic)
    assert cluster.totals["probe"] >= 1
    cluster.submit(Request(prompt_ids=_prompt(3), max_new_tokens=4))
    recs = drive_cluster(cluster, clock)
    assert all(r.outcome == "completed" for r in recs)
    assert r0.state == "live"          # both probes completed -> rejoin
    assert r0.backoff_attempt == 0     # healthy rejoin resets the ladder
    # a second kill backs off from the base again after the reset
    cluster.kill_replica(0, "chaos")
    second_backoff = r0.respawn_at - clock.now
    assert 1.0 <= second_backoff <= 1.0 * 1.25


def test_repeated_kills_back_off_exponentially():
    rs = RespawnConfig(backoff_base_s=1.0, backoff_factor=2.0,
                       backoff_max_s=30.0, jitter_frac=0.0,
                       half_open_probes=1)
    cluster, clock, _ = _fleet(2, respawn=rs)
    r0 = cluster.replicas[0]
    backoffs = []
    for _ in range(3):
        cluster.kill_replica(0, "chaos")
        backoffs.append(r0.respawn_at - clock.now)
        clock.set_time(r0.respawn_at)
        cluster._tick()            # respawn fires; replica goes probing
        assert r0.state == "probing"
        r0.state = "live"          # skip the probe phase for this ladder test
    assert backoffs == [1.0, 2.0, 4.0]


def test_failed_half_open_probe_rekills_the_replica():
    rs = RespawnConfig(backoff_base_s=1.0, backoff_factor=2.0,
                       jitter_frac=0.0, half_open_probes=1)
    cluster, clock, fronts = _fleet(2, respawn=rs)
    cluster.kill_replica(0, "chaos")
    clock.set_time(cluster.replicas[0].respawn_at)
    cluster._tick()
    assert cluster.replicas[0].state == "probing"
    # the probe request fails replica-fatally on the respawned front
    fronts[(0, 1)].inject_fault("stage_lost:0")
    cluster.submit(Request(prompt_ids=_prompt(5), max_new_tokens=4))
    recs = drive_cluster(cluster, clock)
    # the probe request itself was re-admitted and completed elsewhere
    assert all(r.outcome == "completed" for r in recs)
    assert cluster.replicas[0].state in ("dead", "probing")
    assert len(cluster.kills) >= 2
    assert cluster.kills[1]["reason"] == "probe_failed"


# ---------------------------------------------------------------------------
# flight recorder: exactly one post-mortem per induced failure
# ---------------------------------------------------------------------------


def test_exactly_one_flight_dump_per_kill(tmp_path):
    cluster, clock, _ = _fleet(3, flight_dir=str(tmp_path))
    for i in range(6):
        cluster.submit(Request(prompt_ids=_prompt(i), max_new_tokens=8))
    cluster.kill_replica(0, "chaos")
    cluster.kill_replica(1, "chaos")
    drive_cluster(cluster, clock)
    dumps = cluster.flight_dumps()
    assert len(dumps) == 2 == len(cluster.kills)
    assert all(os.path.exists(d) for d in dumps)


# ---------------------------------------------------------------------------
# no live replica: typed refusal, accepted work parks instead of dropping
# ---------------------------------------------------------------------------


def test_no_live_replica_refuses_new_and_parks_accepted():
    rs = RespawnConfig(backoff_base_s=100.0, jitter_frac=0.0)
    cluster, clock, _ = _fleet(2, respawn=rs)
    accepted = cluster.submit(Request(prompt_ids=_prompt(1),
                                      max_new_tokens=8))
    cluster.kill_replica(0, "chaos")
    cluster.kill_replica(1, "chaos")
    refused = cluster.submit(Request(prompt_ids=_prompt(2), max_new_tokens=8))
    recs = cluster.drain()
    assert [r.request_id for r in recs] == [refused]
    assert recs[0].outcome == "rejected"
    assert recs[0].reason == "no_live_replica"
    # the accepted request parked — and completes once a respawn lands
    assert cluster.pending == 1
    clock.advance(200.0)
    recs = drive_cluster(cluster, clock)
    assert [r.request_id for r in recs] == [accepted]
    assert recs[0].outcome == "completed"


# ---------------------------------------------------------------------------
# autoscaler: pressure-driven with min-dwell hysteresis
# ---------------------------------------------------------------------------


def test_autoscaler_scales_up_and_respects_min_dwell():
    asc = AutoscalerConfig(enabled=True, min_replicas=2, max_replicas=4,
                           scale_up_pressure=0.5, scale_down_pressure=0.05,
                           min_dwell_s=30.0)
    sim = SimReplicaConfig(max_queue_depth=4)
    cluster, clock, _ = _fleet(2, autoscaler=asc, sim_cfg=sim)
    for i in range(8):   # saturate both queues -> pressure 1.0
        cluster.submit(Request(prompt_ids=_prompt(i), max_new_tokens=4))
    # the dwell clock starts at construction: saturation inside the first
    # window must NOT scale
    assert not cluster.autoscale_events
    assert len(cluster.replicas) == 2
    clock.advance(asc.min_dwell_s + 1.0)
    cluster.submit(Request(prompt_ids=_prompt(99), max_new_tokens=4))
    ups = [e for e in cluster.autoscale_events if e["direction"] == "up"]
    assert len(ups) == 1, "dwell must allow exactly one scale-up per window"
    assert len(cluster.replicas) == 3
    # still saturated inside the NEW dwell window: no flapping
    cluster.submit(Request(prompt_ids=_prompt(100), max_new_tokens=4))
    assert len(cluster.replicas) == 3
    clock.advance(asc.min_dwell_s + 1.0)
    cluster.submit(Request(prompt_ids=_prompt(101), max_new_tokens=4))
    assert len(cluster.replicas) == 4
    recs = drive_cluster(cluster, clock)
    assert all(r.outcome == "completed" for r in recs)
    # fleet idle: the next dwell window allows exactly one scale-down
    clock.advance(asc.min_dwell_s + 1.0)
    cluster._tick()
    downs = [e for e in cluster.autoscale_events if e["direction"] == "down"]
    assert len(downs) == 1
    assert len(cluster.replicas) == 3


# ---------------------------------------------------------------------------
# cluster chaos soak (small-n shape of the million-request run)
# ---------------------------------------------------------------------------


def test_cluster_soak_chaos_identity_and_zero_loss(tmp_path):
    soak = ClusterSoakConfig(
        n_requests=400, arrival_rate=60.0, seed=3,
        prompt_len=16, shared_prefix_len=8, num_prefix_groups=8,
        max_new_tokens=16, deadline_s=120.0,
        sampled_frac=0.5, sample_temperature=0.7,
        kills=((0.3, 0), (0.55, 1)),
        burst_start_frac=0.4, burst_end_frac=0.6, burst_corrupt_rate=0.05)
    clock = FakeClock()

    def factory(rid, gen):
        return SimReplicaFront(SimReplicaConfig(), clock=clock,
                               replica_id=rid)

    cluster = ClusterFront(
        factory,
        ClusterConfig(num_replicas=3,
                      flight_dir=str(tmp_path / "flight"),
                      checkpoint_dir=str(tmp_path / "ckpt"),
                      respawn=RespawnConfig(backoff_base_s=0.2,
                                            jitter_seed=1)),
        clock=clock)
    art = run_cluster_soak(cluster, soak, clock=clock)
    # zero accepted loss: every request terminal, exactly once
    assert sum(art["outcomes"].values()) == soak.n_requests
    # every completed request replayed token-identical to the fault-free
    # reference — through two kills and a corruption burst
    ti = art["token_identity"]
    assert ti["ok"], ti
    assert ti["checked"] == art["outcomes"]["completed"] > 0
    # the burst produced terminal corruption failures, the kills produced
    # readmissions, and each induced kill dumped exactly one post-mortem
    assert art["reasons"].get("substituted_payload", 0) > 0
    assert art["readmitted"] > 0
    assert len(art["kills"]) == 2
    assert len(art["flight_dumps"]) == 2
    assert all(ev["recovery_s"] is not None for ev in art["kills"])
    assert art["respawns"] == 2
    # goodput series exists for the outage-window gate
    assert art["goodput_buckets"]["tokens"]


def test_cluster_soak_requires_fake_clock():
    clock = FakeClock()
    cluster, _, _ = _fleet(2, clock=clock)
    with pytest.raises(TypeError):
        run_cluster_soak(cluster, ClusterSoakConfig(n_requests=1),
                         clock=None)


def test_soak_config_validation():
    with pytest.raises(ValueError):
        ClusterSoakConfig(shared_prefix_len=20, prompt_len=16)
    with pytest.raises(ValueError):
        ClusterSoakConfig(kills=((1.5, 0),))
    with pytest.raises(ValueError):
        ClusterSoakConfig(burst_start_frac=0.6, burst_end_frac=0.4)
