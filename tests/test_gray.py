"""Gray-failure hardening (PR 20): straggler detection, request hedging,
and deadline propagation.

Correctness anchors, in order of importance:

- hedging is exactly-once: every settled hedge has ONE winning record
  (the loser is cancelled or its late record swallowed), every completed
  request is token-identical to the fault-free reference, and nothing
  accepted is lost;
- the StragglerDetector's windowed quantile math is bit-compatible with
  the numpy linear-interpolation reference, under FakeClock advance and
  window expiry;
- verdicts carry min-dwell hysteresis in BOTH directions and re-promotion
  requires fresh measurements — a flagged key with an empty window stays
  flagged;
- deadline budgets are re-checked at every hop: a parked request whose
  deadline lapsed before re-placement finishes as ``timed_out`` instead
  of being served late, and a deadline-propagating replica refuses
  expired work at prefill/decode chunk boundaries with the typed
  ``deadline_expired`` reason;
- parked requests re-place in ARRIVAL order (no starvation of the oldest
  parked request when kills shuffled the park queue);
- a sustained-slow migration link degrades the disagg front to colocated
  with the typed ``migration_link_slow`` reason, symmetric with the
  dead-link path;
- the seeded gray soak is byte-deterministic: same seed + slowdown
  schedule -> identical artifact.
"""
import dataclasses
import json

import numpy as np
import pytest

from edgellm_tpu.serve import Request
from edgellm_tpu.serve.cluster import (ClusterConfig, ClusterConfigError,
                                       ClusterFront, GrayConfig,
                                       RespawnConfig, SimReplicaConfig,
                                       SimReplicaFront, drive_cluster)
from edgellm_tpu.serve.overload import (DeadlineExpired, StragglerConfig,
                                        StragglerDetector, _linear_quantile)
from edgellm_tpu.serve.soak import ClusterSoakConfig, run_cluster_soak
from edgellm_tpu.utils.clock import FakeClock


def _prompt(seed, n=16, vocab=50_000):
    rng = np.random.default_rng(seed)
    return rng.integers(1, vocab, size=n).astype(np.int32)


def _fleet(n=2, clock=None, sim_cfg=None, **cfg_kw):
    clock = clock if clock is not None else FakeClock()
    scfg = sim_cfg if sim_cfg is not None else SimReplicaConfig()
    fronts = {}

    def factory(rid, gen):
        f = SimReplicaFront(scfg, clock=clock, replica_id=rid)
        fronts[(rid, gen)] = f
        return f

    cluster = ClusterFront(factory, ClusterConfig(num_replicas=n, **cfg_kw),
                           clock=clock)
    return cluster, clock, fronts


def _drive_front(front, clock, max_steps=10_000):
    """Drain one SimReplicaFront to quiescence on the virtual clock."""
    recs = []
    for _ in range(max_steps):
        got = front.drain()
        if got:
            recs.extend(got)
            continue
        ev = front.next_event_s()
        if ev is None:
            return recs
        clock.set_time(max(ev, clock.now))
    raise AssertionError("sim front never drained")


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


def test_gray_config_validation():
    GrayConfig()  # defaults valid (disabled)
    with pytest.raises(ClusterConfigError):
        GrayConfig(enabled=1)
    with pytest.raises(ClusterConfigError):
        GrayConfig(p95_multiple=1.0)
    with pytest.raises(ClusterConfigError):
        GrayConfig(hedge_delay_quantile=1.0)
    with pytest.raises(ClusterConfigError):
        GrayConfig(min_dwell_s=-1.0)
    with pytest.raises(ClusterConfigError):
        GrayConfig(max_hedge_fraction=1.5)
    with pytest.raises(ClusterConfigError):
        GrayConfig(min_samples=0)
    with pytest.raises(ClusterConfigError):
        GrayConfig(window_s=0.0)


def test_straggler_config_validation():
    with pytest.raises(ValueError):
        StragglerConfig(p95_multiple=0.5)
    with pytest.raises(ValueError):
        StragglerConfig(quantile=1.0)
    with pytest.raises(ValueError):
        StragglerConfig(min_samples=9, max_samples=8)
    with pytest.raises(ValueError):
        StragglerConfig(min_dwell_s=-0.1)
    with pytest.raises(ValueError):
        SimReplicaConfig(deadline_propagation=1)


# ---------------------------------------------------------------------------
# detector quantile math vs the numpy reference, with window expiry
# ---------------------------------------------------------------------------


def test_detector_quantiles_match_numpy_under_window_expiry():
    ck = FakeClock()
    det = StragglerDetector(StragglerConfig(window_s=10.0, min_samples=4),
                            clock=ck)
    rng = np.random.default_rng(0)
    samples = {"a": [], "b": []}
    for _ in range(25):
        ck.advance(0.7)   # 17.5s span: the early samples expire
        for k, mult in (("a", 1.0), ("b", 3.0)):
            v = float(rng.gamma(2.0, 0.05)) * mult
            det.observe(k, v)
            samples[k].append((ck.now, v))
    horizon = ck.now - 10.0
    pooled = []
    for k in ("a", "b"):
        vals = [v for t, v in samples[k] if t > horizon]
        assert 0 < len(vals) < len(samples[k])   # expiry really happened
        assert det.sample_count(k) == len(vals)
        for q in (0.5, 0.9, 0.95, 0.99):
            assert det.quantile(k, q) == pytest.approx(
                float(np.quantile(vals, q)), rel=1e-12)
        pooled.extend(vals)
    assert det.fleet_quantile(0.5) == pytest.approx(
        float(np.quantile(pooled, 0.5)), rel=1e-12)
    only_a = [v for t, v in samples["a"] if t > horizon]
    assert det.fleet_quantile(0.95, exclude={"b"}) == pytest.approx(
        float(np.quantile(only_a, 0.95)), rel=1e-12)
    # the whole window expires: nothing left to quantile
    ck.advance(20.0)
    assert det.quantile("a") is None
    assert det.sample_count("b") == 0
    assert det.fleet_quantile() is None


def test_linear_quantile_matches_numpy_exactly():
    rng = np.random.default_rng(3)
    for n in (1, 2, 5, 17):
        vals = sorted(rng.standard_exponential(n).tolist())
        for q in (0.0, 0.25, 0.5, 0.95, 1.0):
            assert _linear_quantile(vals, q) == pytest.approx(
                float(np.quantile(vals, q)), rel=1e-12, abs=1e-15)


# ---------------------------------------------------------------------------
# verdicts: flag, dwell hysteresis, re-promotion on re-measure only
# ---------------------------------------------------------------------------


def test_detector_flags_slow_peer_and_repromotes_on_remeasure():
    ck = FakeClock()
    det = StragglerDetector(
        StragglerConfig(p95_multiple=3.0, min_samples=4, min_dwell_s=0.0,
                        window_s=1000.0), clock=ck)
    for _ in range(4):
        ck.advance(0.1)
        det.observe("a", 0.1)
        det.observe("b", 0.1)
    assert det.stragglers() == ()
    for _ in range(8):
        ck.advance(0.1)
        det.observe("a", 0.1)
        det.observe("b", 1.0)
    assert det.is_straggler("b")
    assert not det.is_straggler("a")
    assert det.summary()["demotions"] == 1
    # the window empties: the verdict STANDS — re-promotion requires fresh
    # measurements, never just elapsed time
    ck.advance(5000.0)
    assert det.is_straggler("b")
    # fresh fast samples (with a fleet to compare against) re-promote
    for _ in range(4):
        ck.advance(0.1)
        det.observe("a", 0.1)
        det.observe("b", 0.1)
    assert not det.is_straggler("b")
    assert det.summary()["promotions"] == 1


def test_detector_min_dwell_blocks_flapping():
    ck = FakeClock()
    det = StragglerDetector(
        StragglerConfig(p95_multiple=3.0, min_samples=4, min_dwell_s=5.0,
                        window_s=2.0), clock=ck)
    for i in range(12):
        ck.advance(0.1)
        det.observe("a", 0.1)
        det.observe("b", 1.0 if i >= 8 else 0.1)
    assert det.is_straggler("b")
    flagged_at = ck.now
    # b turns healthy immediately, but the verdict may not flip back
    # inside the dwell window even with fresh fast samples
    while ck.now - flagged_at < 3.0:
        ck.advance(0.1)
        det.observe("a", 0.1)
        det.observe("b", 0.1)
    assert det.is_straggler("b")   # dwell still holds it down
    while ck.now - flagged_at < 6.0:
        ck.advance(0.1)
        det.observe("a", 0.1)
        det.observe("b", 0.1)
    assert not det.is_straggler("b")
    assert det.summary() == {"keys": 2, "flagged": [], "observed":
                             det.summary()["observed"], "demotions": 1,
                             "promotions": 1}


def test_detector_needs_a_fleet_and_min_samples():
    ck = FakeClock()
    det = StragglerDetector(StragglerConfig(min_samples=4, min_dwell_s=0.0),
                            clock=ck)
    # one slow key alone: no fleet to be slower than
    for _ in range(8):
        ck.advance(0.1)
        det.observe("b", 5.0)
    assert not det.is_straggler("b")
    # a peer appears but b is below min fresh samples after expiry: the
    # verdict cannot form from thin evidence
    det2 = StragglerDetector(StragglerConfig(min_samples=4, min_dwell_s=0.0),
                             clock=ck)
    for _ in range(3):
        ck.advance(0.1)
        det2.observe("a", 0.1)
        det2.observe("b", 5.0)
    assert not det2.is_straggler("b")
    with pytest.raises(ValueError):
        det.observe("a", -1.0)


# ---------------------------------------------------------------------------
# deadline propagation inside a replica (typed refusal of expired work)
# ---------------------------------------------------------------------------


def test_sim_replica_refuses_expired_work_with_typed_reason():
    ck = FakeClock()
    front = SimReplicaFront(SimReplicaConfig(deadline_propagation=True),
                            clock=ck, replica_id=0)
    front.submit(Request(prompt_ids=_prompt(1), max_new_tokens=64,
                         deadline_s=0.05))
    recs = _drive_front(front, ck)
    assert [r.outcome for r in recs] == ["timed_out"]
    assert recs[0].reason == DeadlineExpired.reason == "deadline_expired"
    assert recs[0].deadline_met is not True
    # the budget died mid-decode: some tokens were produced, not all
    assert 0 < recs[0].recovery["tokens_done"] < 64


def test_deadline_propagation_off_by_default_serves_late():
    ck = FakeClock()
    front = SimReplicaFront(SimReplicaConfig(), clock=ck, replica_id=0)
    front.submit(Request(prompt_ids=_prompt(1), max_new_tokens=64,
                         deadline_s=0.05))
    recs = _drive_front(front, ck)
    # the PR-19 replica serves to completion (the deadline is only audited
    # at the cluster edge): bit-identical legacy behavior
    assert [r.outcome for r in recs] == ["completed"]
    assert recs[0].deadline_met is False


def test_sim_replica_cancel_exactly_once():
    ck = FakeClock()
    front = SimReplicaFront(SimReplicaConfig(), clock=ck, replica_id=0)
    keep = front.submit(Request(prompt_ids=_prompt(1), max_new_tokens=4))
    drop = front.submit(Request(prompt_ids=_prompt(2), max_new_tokens=4))
    assert front.cancel(drop) is True
    assert front.cancel(drop) is False      # already gone
    assert front.cancel(999_999) is False   # unknown rid
    recs = _drive_front(front, ck)
    assert [r.request_id for r in recs] == [keep]
    # cancelling the in-flight stream clears it too
    running = front.submit(Request(prompt_ids=_prompt(3), max_new_tokens=8))
    front.drain()   # pops the queue: the stream is now _current
    assert front.cancel(running) is True
    assert _drive_front(front, ck) == []


# ---------------------------------------------------------------------------
# deadline re-check at (re-)placement: a parked request cannot be served
# after its budget lapsed (the audit fix)
# ---------------------------------------------------------------------------


def test_parked_request_expires_at_replacement_not_served_late():
    rs = RespawnConfig(backoff_base_s=100.0, jitter_frac=0.0)
    cluster, clock, _ = _fleet(2, respawn=rs)
    crid = cluster.submit(Request(prompt_ids=_prompt(1), max_new_tokens=8,
                                  deadline_s=5.0))
    cluster.kill_replica(0, "chaos")
    cluster.kill_replica(1, "chaos")
    assert cluster.pending == 1   # parked, not lost
    # the respawn lands long after the deadline: re-placement must refuse
    # the expired work instead of serving it late
    clock.advance(200.0)
    recs = drive_cluster(cluster, clock)
    assert [r.request_id for r in recs] == [crid]
    assert recs[0].outcome == "timed_out"
    assert recs[0].reason == "deadline_expired"
    assert recs[0].deadline_met is False
    assert cluster.totals["deadline_expired"] == 1
    assert cluster.pending == 0


# ---------------------------------------------------------------------------
# parked starvation guard: re-placement in ARRIVAL order
# ---------------------------------------------------------------------------


def test_parked_requests_replace_in_arrival_order():
    rs = RespawnConfig(backoff_base_s=100.0, jitter_frac=0.0)
    cluster, clock, _ = _fleet(2, respawn=rs)
    first = cluster.submit(Request(prompt_ids=_prompt(1), max_new_tokens=4))
    second = cluster.submit(Request(prompt_ids=_prompt(2), max_new_tokens=4))
    # killing replica 0 re-admits `first` to the TAIL of replica 1's
    # queue; killing replica 1 then parks in queue order [second, first] —
    # the park list is now out of arrival order
    cluster.kill_replica(0, "chaos")
    cluster.kill_replica(1, "chaos")
    assert cluster.pending == 2
    clock.advance(200.0)
    recs = drive_cluster(cluster, clock)
    # the starvation guard re-places oldest-first: `first` lands on the
    # first replica slot and finishes ahead of `second`
    assert [r.request_id for r in recs] == [first, second]
    assert all(r.outcome == "completed" for r in recs)


# ---------------------------------------------------------------------------
# hedging: exactly-once settlement, token identity, bounded overhead
# ---------------------------------------------------------------------------

GRAY = GrayConfig(enabled=True, min_dwell_s=0.5, min_samples=8,
                  window_s=30.0, max_hedge_fraction=0.4)
SLOWDOWNS = ((0.3, 0, 20.0),)
SOAK_KW = dict(n_requests=300, arrival_rate=30.0, deadline_s=0.5, seed=7)


def _gray_soak(gray, slowdowns, **kw):
    clock = FakeClock()
    scfg = SimReplicaConfig(deadline_propagation=gray.enabled)
    cluster = ClusterFront(
        lambda rid, gen: SimReplicaFront(scfg, clock=clock, replica_id=rid),
        ClusterConfig(num_replicas=3, gray=gray), clock=clock)
    art = run_cluster_soak(cluster, ClusterSoakConfig(
        slowdowns=slowdowns, **kw), clock=clock)
    return art, cluster


def test_hedged_soak_exactly_once_and_token_identity():
    art, cluster = _gray_soak(GRAY, SLOWDOWNS, **SOAK_KW)
    n = SOAK_KW["n_requests"]
    assert sum(art["outcomes"].values()) == n   # zero accepted loss
    assert art["outcomes"].get("failed", 0) == 0
    assert cluster.pending == 0
    assert art["hedges"] > 0
    t = cluster.totals
    # every hedge settled exactly once: one winning leg, one loser that
    # was cancelled or had its late record swallowed
    assert t["hedge_wins_primary"] + t["hedge_wins_hedge"] == t["hedges"]
    assert t["hedge_cancelled"] + t["hedge_discarded"] == t["hedges"]
    assert art["hedge_fraction"] <= GRAY.max_hedge_fraction + 1e-9
    # first-finisher-wins never surfaces a duplicate or divergent stream
    ident = art["token_identity"]
    assert ident["ok"] and ident["checked"] > 0
    assert ident["mismatched_ids"] == []
    # the gray plane beats the unhedged fleet on the same slowdown (the
    # full 1.5x gate runs at bench scale, BENCH_GRAY=1)
    base, _ = _gray_soak(GrayConfig(), SLOWDOWNS, **SOAK_KW)
    assert base["hedges"] == 0
    assert base["slo_goodput"] < 0.9 < art["slo_goodput"]
    assert art["slo_goodput"] > base["slo_goodput"]


def test_hedge_disabled_fleet_runs_no_gray_machinery():
    art, cluster = _gray_soak(GrayConfig(), (), **SOAK_KW)
    assert art["gray"] is None
    assert art["hedges"] == 0 and art["deadline_expired"] == 0
    assert cluster.report()["gray"] is None
    assert sum(art["outcomes"].values()) == SOAK_KW["n_requests"]


def test_gray_soak_is_byte_deterministic():
    a1, _ = _gray_soak(GRAY, SLOWDOWNS, **SOAK_KW)
    a2, _ = _gray_soak(GRAY, SLOWDOWNS, **SOAK_KW)
    assert (json.dumps(a1, sort_keys=True, default=float)
            == json.dumps(a2, sort_keys=True, default=float))


def test_soak_slowdown_schedule_validation():
    with pytest.raises(ValueError):
        ClusterSoakConfig(slowdowns=((1.5, 0, 2.0),))
    with pytest.raises(ValueError):
        ClusterSoakConfig(slowdowns=((0.5, 0, 0.5),))
    with pytest.raises(ValueError):
        SimReplicaFront(SimReplicaConfig(),
                        clock=FakeClock()).set_service_multiplier(0.0)


# ---------------------------------------------------------------------------
# slow migration link: degrade-to-colocated with the typed reason
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_slow_migration_link_degrades_with_typed_reason():
    import jax

    from edgellm_tpu.models import init_params, tiny_config
    from edgellm_tpu.serve.batching import BatchingConfig, ContinuousBatcher
    from edgellm_tpu.serve.disagg import (DEGRADE_LINK_SLOW, DisaggConfig,
                                          DisaggServer)

    cfg = tiny_config("qwen2", num_layers=2, hidden_size=32, num_heads=4,
                      vocab_size=128)
    params = init_params(cfg, jax.random.key(1))
    bcfg = BatchingConfig(page_size=8, num_pages=17, max_slots=4,
                          pages_per_slot=4)
    rng = np.random.default_rng(5)

    def reqs(seed0, k=4):
        return [(rng.integers(1, cfg.vocab_size, size=9).astype(np.int32),
                 4, 0.0, seed0 + i) for i in range(k)]

    ck = FakeClock()
    srv = DisaggServer(cfg, params, bcfg, DisaggConfig(
        num_prefill_workers=1, transfer_s_per_page=0.01,
        slow_link_p95_multiple=3.0, slow_link_min_samples=4,
        slow_link_window_s=1e9), clock=ck)
    ref = ContinuousBatcher(cfg, params, bcfg)

    def serve_and_check(batch):
        ref_sids = [ref.submit(p, m, temperature=t, rng_seed=s)
                    for p, m, t, s in batch]
        want = ref.run()
        sids = [srv.submit(p, m, temperature=t, rng_seed=s)
                for p, m, t, s in batch]
        got = srv.run()
        for rs, ss in zip(ref_sids, sids):
            assert np.array_equal(want[rs], got[ss])

    # healthy phase: enough transfers to freeze the baseline median
    serve_and_check(reqs(0, k=6))
    assert not srv.degraded
    rep = srv.report()["disagg"]
    assert rep["transfer_baseline_s"] is not None
    # the link goes gray: transfers now take 10x the modeled wire time;
    # the windowed p95 crosses 3x baseline and the front demotes itself
    srv.slow_link(10.0)
    serve_and_check(reqs(100, k=6))
    assert srv.degraded
    assert srv.degrade_reason == DEGRADE_LINK_SLOW
    # degraded serving still completes token-identically (colocated path)
    serve_and_check(reqs(200, k=2))


def test_disagg_slow_link_config_validation():
    from edgellm_tpu.serve.disagg import DisaggConfig

    with pytest.raises(ValueError):
        DisaggConfig(slow_link_p95_multiple=0.5)
    with pytest.raises(ValueError):
        DisaggConfig(slow_link_min_samples=1)
    with pytest.raises(ValueError):
        DisaggConfig(slow_link_window_s=0.0)
    with pytest.raises(ValueError):
        DisaggConfig(transfer_s_per_page=-0.1)
    DisaggConfig(slow_link_p95_multiple=0.0)   # 0 disables the detector


# ---------------------------------------------------------------------------
# cluster report/artifact surface
# ---------------------------------------------------------------------------


def test_gray_report_surface():
    art, cluster = _gray_soak(GRAY, SLOWDOWNS, **SOAK_KW)
    rep = cluster.report()
    assert sorted(rep["gray"]) == ["detector", "flagged", "hedge_delay_s"]
    assert rep["gray"]["detector"]["observed"] > 0
    assert art["gray"] == rep["gray"]
    for key in ("hedges", "hedge_wins", "hedge_discarded", "hedge_fraction",
                "deadline_expired", "slo_goodput"):
        assert key in art
    # slo_goodput counts timeouts as misses: met / ALL requests
    met = round(art["slo_goodput"] * SOAK_KW["n_requests"])
    assert met <= art["outcomes"].get("completed", 0)


def test_gray_config_threads_through_cluster_config():
    cc = ClusterConfig(num_replicas=2, gray=GRAY)
    assert cc.gray.enabled
    with pytest.raises(ClusterConfigError):
        ClusterConfig(num_replicas=2, gray={"enabled": True})
    assert dataclasses.asdict(ClusterConfig(num_replicas=2))["gray"][
        "enabled"] is False
