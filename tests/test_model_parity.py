"""Numerical parity of the JAX cores vs HuggingFace torch implementations.

The environment is offline (no pretrained checkpoints), so parity is checked
against *randomly initialized* ``transformers`` models built from small configs —
this validates every architectural detail (fused-QKV head interleaving, partial
rotary, parallel residual, GQA, SwiGLU, norm placement) without network access.
The reference's only correctness check was that its manual layer loop matched the
stock model's perplexity (``qwen_layer_wise.py:78-104``); this is the same idea,
made exact at the logits level.
"""
import numpy as np
import pytest
import torch

torch.manual_seed(0)

from transformers import (GPTNeoXConfig, GPTNeoXForCausalLM, Qwen2Config,
                          Qwen2ForCausalLM, LlamaConfig, LlamaForCausalLM)

import jax.numpy as jnp

from edgellm_tpu.models import (
    config_from_hf, params_from_state_dict, forward, nll_from_logits,
)


def _build_neox():
    hf_cfg = GPTNeoXConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=3, num_attention_heads=4,
        intermediate_size=256, rotary_pct=0.25, max_position_embeddings=128,
        hidden_act="gelu", layer_norm_eps=1e-5, use_parallel_residual=True,
        attn_implementation="eager",
    )
    model = GPTNeoXForCausalLM(hf_cfg).eval()
    return hf_cfg, model


def _build_qwen2():
    hf_cfg = Qwen2Config(
        vocab_size=256, hidden_size=64, num_hidden_layers=3, num_attention_heads=4,
        num_key_value_heads=2, intermediate_size=128, max_position_embeddings=128,
        rms_norm_eps=1e-6, rope_theta=10000.0, tie_word_embeddings=True,
        attn_implementation="eager",
    )
    model = Qwen2ForCausalLM(hf_cfg).eval()
    return hf_cfg, model


def _build_llama():
    hf_cfg = LlamaConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=3, num_attention_heads=4,
        num_key_value_heads=2, intermediate_size=128, max_position_embeddings=128,
        rms_norm_eps=1e-5, rope_theta=500000.0, tie_word_embeddings=True,
        attention_bias=False, attn_implementation="eager",
        rope_scaling={"rope_type": "llama3", "factor": 32.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 64},
    )
    model = LlamaForCausalLM(hf_cfg).eval()
    return hf_cfg, model


@pytest.fixture(scope="module", params=["gpt_neox", "qwen2", "llama"])
def family_setup(request):
    builder = {"gpt_neox": _build_neox, "qwen2": _build_qwen2,
               "llama": _build_llama}[request.param]
    hf_cfg, model = builder()
    cfg = config_from_hf(hf_cfg)
    params = params_from_state_dict(cfg, model.state_dict())
    ids = np.random.default_rng(1).integers(0, hf_cfg.vocab_size, size=(1, 48))
    with torch.no_grad():
        out = model(torch.tensor(ids), output_attentions=True)
    return cfg, params, ids, out


def test_logits_parity(family_setup):
    cfg, params, ids, hf_out = family_setup
    logits, _ = forward(cfg, params, jnp.asarray(ids))
    ref = hf_out.logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), ref, atol=2e-4, rtol=2e-3)


def test_attention_stats_parity(family_setup):
    cfg, params, ids, hf_out = family_setup
    _, aux = forward(cfg, params, jnp.asarray(ids), capture_stats=True)
    stats = aux["stats"]
    for layer, attn in enumerate(hf_out.attentions):
        a = attn.numpy()  # (B, H, S, S)
        np.testing.assert_allclose(
            np.asarray(stats.col_mean[layer]), a.mean(axis=2), atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(stats.last_row[layer]), a[:, :, -1, :], atol=1e-5, rtol=1e-4)


def test_nll_matches_torch_cross_entropy(family_setup):
    cfg, params, ids, hf_out = family_setup
    logits, _ = forward(cfg, params, jnp.asarray(ids))
    targets = np.array(ids)
    targets[:, :5] = -100  # mimic the harness's overlap masking
    nll = nll_from_logits(logits, jnp.asarray(targets))
    t_logits = hf_out.logits[:, :-1, :].reshape(-1, hf_out.logits.shape[-1])
    t_targets = torch.tensor(targets[:, 1:]).reshape(-1)
    ref = torch.nn.functional.cross_entropy(t_logits, t_targets, ignore_index=-100)
    np.testing.assert_allclose(float(nll), float(ref), atol=1e-5, rtol=1e-5)


def test_boundary_fn_interception(family_setup):
    """boundary_fn edits the hidden state after exactly the targeted layer."""
    cfg, params, ids, _ = family_setup

    def zero_at_layer1(idx, h):
        return jnp.where(idx == 1, jnp.zeros_like(h), h)

    base, _ = forward(cfg, params, jnp.asarray(ids))
    edited, _ = forward(cfg, params, jnp.asarray(ids), boundary_fn=zero_at_layer1)
    assert not np.allclose(np.asarray(base), np.asarray(edited))

    def noop(idx, h):
        return jnp.where(idx == 99, jnp.zeros_like(h), h)

    same, _ = forward(cfg, params, jnp.asarray(ids), boundary_fn=noop)
    np.testing.assert_allclose(np.asarray(base), np.asarray(same), atol=1e-6)


class TestBlockedTailCE:
    """Vocab-blocked streaming CE vs the full-logits oracle (vocab_block=0):
    identical NLLs without materializing the (rows, V) logits tensor."""

    def _setup(self, family, tie):
        import jax
        from edgellm_tpu.models import tiny_config, init_params

        cfg = tiny_config(family, num_layers=2, hidden_size=32, num_heads=4,
                          vocab_size=128)
        if cfg.tie_word_embeddings != tie:
            cfg = cfg.__class__(**{**cfg.__dict__, "tie_word_embeddings": tie})
        return cfg, init_params(cfg, jax.random.key(7))

    @pytest.mark.parametrize("family,tie", [("qwen2", True), ("qwen2", False),
                                            ("gpt_neox", False)])
    @pytest.mark.parametrize("vb", [32, 64])
    def test_matches_full_logits(self, rng, family, tie, vb):
        import jax.numpy as jnp
        from edgellm_tpu.models.transformer import nll_tail

        cfg, params = self._setup(family, tie)
        hidden = jnp.asarray(rng.normal(size=(3, 16, 32)).astype(np.float32))
        targets = np.asarray(rng.integers(0, 128, (3, 16)))
        targets[:, :10] = -100  # windowing mask
        targets[2, :] = -100  # one fully-masked row
        targets = jnp.asarray(targets)
        for per_example in (False, True):
            want = nll_tail(cfg, params, hidden, targets, tail=7,
                            per_example=per_example, vocab_block=0)
            got = nll_tail(cfg, params, hidden, targets, tail=7,
                           per_example=per_example, vocab_block=vb)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=1e-5, rtol=1e-5)

    def test_auto_blocks_only_large_vocabs(self):
        from edgellm_tpu.models.transformer import _vocab_block_size

        assert _vocab_block_size(128) == 128  # tiny: single block (old path)
        assert _vocab_block_size(151936) == 4748  # Qwen2: 32 blocks
        assert 151936 % _vocab_block_size(151936) == 0
        assert _vocab_block_size(50304) == 6288  # Pythia: 8 blocks
        assert _vocab_block_size(32000) == 8000  # Llama-2-ish

    def test_bad_block_raises(self, rng):
        import jax.numpy as jnp
        from edgellm_tpu.models.transformer import nll_tail

        cfg, params = self._setup("qwen2", True)
        with pytest.raises(ValueError, match="divide"):
            nll_tail(cfg, params, jnp.zeros((1, 8, 32)), jnp.zeros((1, 8), int),
                     tail=3, vocab_block=33)


def test_auto_blocked_ce_at_realistic_vocab(rng):
    """At real vocabulary sizes the AUTO path streams (Pythia's 50304 -> 8
    blocks of 6288); it must equal the full-logits oracle. Tiny-model tests
    never reach this branch (small vocabs stay single-block)."""
    import jax
    import jax.numpy as jnp
    from edgellm_tpu.models import tiny_config, init_params
    from edgellm_tpu.models.transformer import _vocab_block_size, nll_tail

    cfg = tiny_config("qwen2", num_layers=1, hidden_size=32, num_heads=4,
                      vocab_size=50304)
    assert _vocab_block_size(cfg.vocab_size) == 6288  # auto path really blocks
    params = init_params(cfg, jax.random.key(9))
    hidden = jnp.asarray(rng.normal(size=(2, 12, 32)).astype(np.float32))
    targets = np.asarray(rng.integers(0, cfg.vocab_size, (2, 12)))
    targets[:, :8] = -100
    targets = jnp.asarray(targets)
    want = nll_tail(cfg, params, hidden, targets, tail=5, vocab_block=0)
    got = nll_tail(cfg, params, hidden, targets, tail=5)  # auto
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
