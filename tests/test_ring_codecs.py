"""Importance-guided selective hops under the stage x seq (ring) runtime.

Round-4 capability composition (VERDICT r3 missing #1): the reference's
headline codec — token-selective int4 at the boundary
(``qwen_layer_wise.py:54-73``) — must run while the sequence is ring-sharded,
with the attention-statistic importance captured inside ``ring_attention``'s
rotation itself (no device ever holds the full sequence or an O(S^2) buffer).

Oracles: the dense stats forward (importance parity), the dense
``selective_int4`` split runtime (logit/PPL parity for mode="global"), and the
analytic payload accounting (verified against the actual in-mesh buffers).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from edgellm_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from edgellm_tpu.models import tiny_config, init_params
from edgellm_tpu.models.transformer import run_layers_from_ids
from edgellm_tpu.importance import importance_per_layer
from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh
from edgellm_tpu.parallel.ring import (SplitRingRuntime, importance_sp,
                                       make_seq_mesh, make_sp_stage_mesh,
                                       ring_attention)
from edgellm_tpu.codecs.packing import selective_int4
from edgellm_tpu.codecs.ring_codecs import ring_selective_int4
from edgellm_tpu.eval.split_eval import parse_hop_codec, run_split_eval

CFG = tiny_config("qwen2", num_layers=4, hidden_size=32, num_heads=4,
                  vocab_size=128)


@pytest.fixture(scope="module")
def setup():
    params = init_params(CFG, jax.random.key(2))
    ids = jnp.asarray(np.random.default_rng(8).integers(0, CFG.vocab_size,
                                                        (2, 32)))
    return params, ids


def test_ring_attention_stats_match_dense(rng):
    """col_sum / last_row accumulated in the K rotation == the full-probs
    statistics."""
    b, s, h, hd = 2, 32, 4, 8
    q = jnp.asarray(rng.normal(size=(b, s, h, hd)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, s, h, hd)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, s, h, hd)).astype(np.float32))

    scores = np.einsum("bshd,bthd->bhst", q, k) / np.sqrt(hd)
    mask = np.tril(np.ones((s, s), bool))
    scores = np.where(mask[None, None], scores, -np.inf)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want_col = p.sum(axis=2) / s  # (B, H, S)
    want_last = p[:, :, -1, :]

    mesh = make_seq_mesh(4)
    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", capture_stats=True),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=(P(None, "seq"), (P(None, None, "seq"), P(None, None, "seq"))),
    )(q, k, v)
    _, (col, last) = out
    np.testing.assert_allclose(np.asarray(col), want_col, atol=1e-6)
    np.testing.assert_allclose(np.asarray(last), want_last, atol=1e-6)


@pytest.mark.parametrize("method", ["regular_importance", "last_row",
                                    "aggregate_till", "weighted_importance"])
def test_importance_sp_matches_dense(setup, method):
    """Ring-captured importance == the dense stats forward's, every method."""
    params, ids = setup
    hw = None
    if method == "weighted_importance":
        hw = np.random.default_rng(3).random(
            (CFG.num_layers, CFG.num_heads)).astype(np.float32)
        hw /= hw.sum(axis=1, keepdims=True)
    _, aux = run_layers_from_ids(CFG, params, ids, capture_stats=True)
    dense = importance_per_layer(
        aux["stats"], method, None if hw is None else jnp.asarray(hw))
    ring = importance_sp(CFG, params, ids, make_seq_mesh(4), method,
                         head_weights=hw)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), atol=1e-6)


@pytest.mark.parametrize("batch", [1, 2], ids=["shared", "per_row"])
def test_ring_selective_global_equals_dense_selective(setup, batch):
    """mode="global": identical decoded hidden -> identical logits vs the
    dense selective split runtime, for both importance wire formats."""
    params, ids_full = setup
    ids = ids_full[:batch]
    _, aux = run_layers_from_ids(CFG, params, ids, capture_stats=True)
    imp = importance_per_layer(aux["stats"], "last_row")[1]  # cut layer 1
    imp = imp if batch > 1 else imp[0]

    dense_rt = SplitRuntime(
        CFG, SplitConfig(cuts=(1,), hop_codecs=(selective_int4(0.25, "bf16"),)),
        make_stage_mesh(2))
    want = dense_rt.forward(dense_rt.place_params(params), ids,
                            hop_importance=[imp])

    ring_rt = SplitRingRuntime(
        CFG, (1,), (ring_selective_int4(0.25, "bf16", n_seq=4, mode="global"),),
        make_sp_stage_mesh(2, 4))
    got = ring_rt.forward(ring_rt.place_params(params), ids,
                          hop_importance=[imp])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_selective_local_runs_and_is_wire_optimal(setup):
    """mode="local": shard-local selection; per-token wire bytes match the
    dense codec (no capacity padding), output finite and close to dense."""
    params, ids = setup
    _, aux = run_layers_from_ids(CFG, params, ids, capture_stats=True)
    imp = importance_per_layer(aux["stats"], "last_row")[1]

    ring_rt = SplitRingRuntime(
        CFG, (1,), (ring_selective_int4(0.25, "bf16", n_seq=4, mode="local"),),
        make_sp_stage_mesh(2, 4))
    out = ring_rt.forward(ring_rt.place_params(params), ids,
                          hop_importance=[imp])
    assert np.isfinite(np.asarray(out)).all()

    dense_rt = SplitRuntime(
        CFG, SplitConfig(cuts=(1,), hop_codecs=(selective_int4(0.25, "bf16"),)),
        make_stage_mesh(2))
    s = ids.shape[1]
    local_bpt = ring_rt.bytes_per_token(s)[0]
    dense_bpt = dense_rt.bytes_per_token(s)[0]
    # k rounding across shards can differ by a few tokens; no capacity blowup
    assert abs(local_bpt - dense_bpt) / dense_bpt < 0.05
    # ...whereas the exact global mode pays its documented in-place-high tax
    global_rt = SplitRingRuntime(
        CFG, (1,), (ring_selective_int4(0.25, "bf16", n_seq=4, mode="global"),),
        make_sp_stage_mesh(2, 4))
    assert global_rt.bytes_per_token(s)[0] > dense_bpt


@pytest.mark.parametrize("mode", ["global", "local"])
@pytest.mark.parametrize("per_row", [True, False])
def test_ring_payload_accounting_matches_buffers(setup, mode, per_row):
    """The analytic payload_bytes equals the actual bytes of the per-shard
    encode buffers (summed over shards) — for BOTH wire formats: per-row
    (B, S) importance and shared (S,) importance, whose scale/index side
    channels are batch-independent (ADVICE r4)."""
    params, ids = setup
    b, s, d = 2, 32, CFG.hidden_size
    n_seq = 4
    codec = ring_selective_int4(0.25, "bf16", n_seq=n_seq, mode=mode)
    h = jnp.asarray(np.random.default_rng(5).normal(size=(b, s, d)),
                    jnp.float32)
    imp_shape = (b, s) if per_row else (s,)
    imp = jnp.asarray(np.random.default_rng(6).random(imp_shape), jnp.float32)
    mesh = make_seq_mesh(n_seq)
    imp_spec = P(None, "seq") if per_row else P("seq")
    payload = shard_map(
        codec.encode, mesh=mesh,
        in_specs=(P(None, "seq"), imp_spec),
        # concatenating every leaf over the ring axis makes the global leaf
        # sizes the sum of the per-shard payload sizes
        out_specs=jax.tree_util.tree_map(lambda _: P("seq"),
                                         {"low": 0, "scale": 0, "high": 0,
                                          "idx" if mode == "global"
                                          else "order": 0}),
        check_vma=False,
    )(h, imp)
    actual = sum(np.asarray(v).nbytes for v in
                 jax.tree_util.tree_leaves(payload))
    assert actual == codec.payload_bytes((b, s, d), per_row=per_row)


def test_split_eval_ring_selective_equals_plain(setup, tmp_path):
    """THE round-4 criterion: stage x seq split-eval with selective_int4:0.25
    equals the plain split-eval PPL — importance captured in the ring, hops
    crossing as mixed int4/bf16 sequence shards."""
    params, _ = setup
    corpus = np.random.default_rng(11).integers(0, CFG.vocab_size, 32 + 16 * 5)
    kw = dict(cuts=(1,), hop_codecs=("selective_int4:0.25:bf16",),
              importance_method="last_row", max_length=32, stride=16,
              time_hops=False)
    plain = run_split_eval(CFG, params, corpus, **kw)
    ring = run_split_eval(CFG, params, corpus, n_seq=2,
                          mesh=make_sp_stage_mesh(2, 2), **kw)
    np.testing.assert_allclose(ring["ppl"], plain["ppl"], rtol=1e-5)
    assert ring["hop_codecs"] == ["ring_selective_int4_r0.25_bf16_global"]
    assert ring["chunks"] == plain["chunks"]


def test_split_eval_ring_selective_local_mode(setup):
    """The wire-optimal local mode through the driver: explicit :local spec,
    finite PPL in the same ballpark as the exact global mode."""
    params, _ = setup
    corpus = np.random.default_rng(11).integers(0, CFG.vocab_size, 32 + 16 * 3)
    kw = dict(cuts=(1,), importance_method="last_row", max_length=32,
              stride=16, time_hops=False, n_seq=2)
    glob = run_split_eval(CFG, params, corpus, mesh=make_sp_stage_mesh(2, 2),
                          hop_codecs=("selective_int4:0.25:bf16",), **kw)
    loc = run_split_eval(CFG, params, corpus, mesh=make_sp_stage_mesh(2, 2),
                         hop_codecs=("selective_int4:0.25:bf16:local",), **kw)
    assert np.isfinite(loc["ppl"])
    assert loc["hop_codecs"] == ["ring_selective_int4_r0.25_bf16_local"]
    # different selection set, same compression: PPLs close but not equal.
    # The asserted |dNLL| bound (0.02) is >10x the worst value measured at
    # the flagship ring shape — qwen2-0.5b / cut 11 / S=2048 / n_seq=4 gave
    # |dNLL| <= 8.4e-4 (ratio 0.25) and <= 1.6e-3 (ratio 0.5); see
    # tools/ring_mode_gap.py and the MULTICHIP artifact's
    # ring_selective_local entry
    d_nll = abs(float(np.log(loc["ppl"])) - float(np.log(glob["ppl"])))
    assert d_nll <= 0.02, d_nll
    assert loc["bytes_per_token_per_hop"][0] < glob["bytes_per_token_per_hop"][0]


def test_ring_codec_validation():
    with pytest.raises(ValueError, match="ratio"):
        ring_selective_int4(1.5, n_seq=2)
    with pytest.raises(ValueError, match="mode"):
        ring_selective_int4(0.5, n_seq=2, mode="nope")
    # n_seq mismatch between codec and mesh is rejected
    with pytest.raises(ValueError, match="ring codec"):
        SplitRingRuntime(CFG, (1,),
                         (ring_selective_int4(0.25, n_seq=4, mode="global"),),
                         make_sp_stage_mesh(2, 2))
    # dense selective (not ring-aware) still rejected under "seq"
    with pytest.raises(ValueError, match="ring-aware"):
        SplitRingRuntime(CFG, (1,), (selective_int4(0.25),),
                         make_sp_stage_mesh(2, 2))
    # local/global mode spec only parses for the ring path
    with pytest.raises(ValueError, match="stage x seq"):
        parse_hop_codec("selective_int4:0.25:bf16:local", n_seq=1)
