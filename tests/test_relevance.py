"""LRP relevance tests.

``lxt`` is not installed in this environment (reference dep), so the oracle is an
independent *torch autograd* implementation of the same LRP rules — detached
normalizers, uniform product rule, probs with ``retain_grad``, seed
``backward(max_logits)`` — built directly on the HF state_dict weights. Two
different autograd engines computing the same modified-gradient semantics must
agree on the per-head relevance.
"""
import numpy as np
import pytest
import torch

from transformers import Qwen2Config, Qwen2ForCausalLM

import jax
import jax.numpy as jnp

from edgellm_tpu.models import config_from_hf, params_from_state_dict
from edgellm_tpu.importance.relevance import (
    uniform_mul, lrp_forward, run_relevance_extraction, _chunk_relevance,
)

torch.manual_seed(0)


class _HalfProduct(torch.autograd.Function):
    """torch twin of the uniform LRP product rule."""

    @staticmethod
    def forward(ctx, a, b):
        ctx.save_for_backward(a, b)
        return a * b

    @staticmethod
    def backward(ctx, g):
        a, b = ctx.saved_tensors
        return 0.5 * g * b, 0.5 * g * a


def _torch_lrp_relevance(model, ids):
    """Manual torch forward with LRP rules on the HF weights; returns (L, H)."""
    cfg = model.config
    sd = {k: v.float() for k, v in model.state_dict().items()}
    h_, kv = cfg.num_attention_heads, cfg.num_key_value_heads
    hd = cfg.hidden_size // h_
    x = sd["model.embed_tokens.weight"][ids]  # (B, S, D)
    B, S, D = x.shape

    pos = torch.arange(S, dtype=torch.float32)
    inv = 1.0 / (cfg.rope_theta ** (torch.arange(0, hd, 2, dtype=torch.float32) / hd))
    freqs = torch.outer(pos, inv)
    emb = torch.cat([freqs, freqs], dim=-1)
    cos, sin = emb.cos(), emb.sin()

    def rot(t):  # (B, S, H, hd)
        c, s_ = cos[None, :, None, :], sin[None, :, None, :]
        half = t.shape[-1] // 2
        rotated = torch.cat([-t[..., half:], t[..., :half]], dim=-1)
        return t * c + rotated * s_

    def rmsnorm_lrp(v, w):
        denom = torch.rsqrt(v.pow(2).mean(-1, keepdim=True) + cfg.rms_norm_eps).detach()
        return v * denom * w

    probs_saved = []
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        a_in = rmsnorm_lrp(x, sd[p + "input_layernorm.weight"])
        q = (a_in @ sd[p + "self_attn.q_proj.weight"].T + sd[p + "self_attn.q_proj.bias"]).view(B, S, h_, hd)
        k = (a_in @ sd[p + "self_attn.k_proj.weight"].T + sd[p + "self_attn.k_proj.bias"]).view(B, S, kv, hd)
        v = (a_in @ sd[p + "self_attn.v_proj.weight"].T + sd[p + "self_attn.v_proj.bias"]).view(B, S, kv, hd)
        q, k = rot(q), rot(k)
        k = k.repeat_interleave(h_ // kv, dim=2)
        v = v.repeat_interleave(h_ // kv, dim=2)
        scores = torch.einsum("bshd,bthd->bhst", q, k) / np.sqrt(hd)
        mask = torch.tril(torch.ones(S, S, dtype=torch.bool))
        scores = scores.masked_fill(~mask, torch.finfo(torch.float32).min)
        probs = torch.softmax(scores, dim=-1)
        probs.requires_grad_(True)
        probs.retain_grad()
        probs_saved.append(probs)
        attn = torch.einsum("bhst,bthd->bshd", probs, v).reshape(B, S, h_ * hd)
        x = x + attn @ sd[p + "self_attn.o_proj.weight"].T
        m_in = rmsnorm_lrp(x, sd[p + "post_attention_layernorm.weight"])
        gate = torch.nn.functional.silu(m_in @ sd[p + "mlp.gate_proj.weight"].T)
        up = m_in @ sd[p + "mlp.up_proj.weight"].T
        x = x + _HalfProduct.apply(gate, up) @ sd[p + "mlp.down_proj.weight"].T

    post = rmsnorm_lrp(x, sd["model.norm.weight"])
    logits = post @ sd["model.embed_tokens.weight"].T
    max_logits, _ = torch.max(logits[:, -1, :], dim=-1)
    max_logits.backward(max_logits)
    rel = [(p * p.grad).sum(dim=(0, 2, 3)).detach().numpy() for p in probs_saved]
    return np.stack(rel)


def _torch_lrp_relevance_neox(model, ids):
    """Manual torch LRP forward for GPT-NeoX: parallel residual, LayerNorm with
    detached rsqrt(var), fused QKV head-interleaved layout, partial rotary,
    standard-gradient GELU -> (L, H) head relevance."""
    cfg = model.config
    sd = {k: v.float() for k, v in model.state_dict().items()}
    h_ = cfg.num_attention_heads
    hd = cfg.hidden_size // h_
    rot = int(hd * cfg.rotary_pct)
    x = sd["gpt_neox.embed_in.weight"][ids]
    B, S, D = x.shape

    pos = torch.arange(S, dtype=torch.float32)
    inv = 1.0 / (cfg.rotary_emb_base ** (torch.arange(0, rot, 2, dtype=torch.float32) / rot))
    emb = torch.cat([torch.outer(pos, inv)] * 2, dim=-1)
    cos, sin = emb.cos()[None, :, None, :], emb.sin()[None, :, None, :]

    def rope(t):
        t_rot, t_pass = t[..., :rot], t[..., rot:]
        half = rot // 2
        rotated = torch.cat([-t_rot[..., half:], t_rot[..., :half]], dim=-1)
        return torch.cat([t_rot * cos + rotated * sin, t_pass], dim=-1)

    def ln_lrp(v, w, b, eps):
        mu = v.mean(-1, keepdim=True)
        denom = torch.rsqrt(v.var(-1, keepdim=True, unbiased=False) + eps).detach()
        return (v - mu) * denom * w + b

    probs_saved = []
    for i in range(cfg.num_hidden_layers):
        p = f"gpt_neox.layers.{i}."
        a_in = ln_lrp(x, sd[p + "input_layernorm.weight"],
                      sd[p + "input_layernorm.bias"], cfg.layer_norm_eps)
        qkv = (a_in @ sd[p + "attention.query_key_value.weight"].T
               + sd[p + "attention.query_key_value.bias"]).view(B, S, h_, 3, hd)
        q, k, v = rope(qkv[..., 0, :]), rope(qkv[..., 1, :]), qkv[..., 2, :]
        scores = torch.einsum("bshd,bthd->bhst", q, k) / np.sqrt(hd)
        mask = torch.tril(torch.ones(S, S, dtype=torch.bool))
        scores = scores.masked_fill(~mask, torch.finfo(torch.float32).min)
        probs = torch.softmax(scores, dim=-1)
        probs.requires_grad_(True)
        probs.retain_grad()
        probs_saved.append(probs)
        attn = torch.einsum("bhst,bthd->bshd", probs, v).reshape(B, S, h_ * hd)
        attn = attn @ sd[p + "attention.dense.weight"].T + sd[p + "attention.dense.bias"]
        m_in = ln_lrp(x, sd[p + "post_attention_layernorm.weight"],
                      sd[p + "post_attention_layernorm.bias"], cfg.layer_norm_eps)
        mlp = torch.nn.functional.gelu(
            m_in @ sd[p + "mlp.dense_h_to_4h.weight"].T + sd[p + "mlp.dense_h_to_4h.bias"])
        mlp = mlp @ sd[p + "mlp.dense_4h_to_h.weight"].T + sd[p + "mlp.dense_4h_to_h.bias"]
        x = x + attn + mlp  # parallel residual
    post = ln_lrp(x, sd["gpt_neox.final_layer_norm.weight"],
                  sd["gpt_neox.final_layer_norm.bias"], cfg.layer_norm_eps)
    logits = post @ sd["embed_out.weight"].T
    max_logits, _ = torch.max(logits[:, -1, :], dim=-1)
    max_logits.backward(max_logits)
    rel = [(p_ * p_.grad).sum(dim=(0, 2, 3)).detach().numpy() for p_ in probs_saved]
    return np.stack(rel)


def test_neox_head_relevance_matches_torch_lrp_oracle():
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_cfg = GPTNeoXConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=3, num_attention_heads=4,
        intermediate_size=256, rotary_pct=0.25, max_position_embeddings=128,
        hidden_act="gelu", layer_norm_eps=1e-5, use_parallel_residual=True,
        attn_implementation="eager",
    )
    model = GPTNeoXForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg)
    params = params_from_state_dict(cfg, model.state_dict())
    ids = np.random.default_rng(11).integers(0, 256, size=(1, 18))
    got = np.asarray(_chunk_relevance(cfg)(params, jnp.asarray(ids)))
    want = _torch_lrp_relevance_neox(model, torch.tensor(ids))
    assert got.shape == want.shape == (3, 4)
    np.testing.assert_allclose(got, want, atol=1e-3, rtol=1e-3)


@pytest.fixture(scope="module")
def qwen_setup():
    hf_cfg = Qwen2Config(
        vocab_size=256, hidden_size=64, num_hidden_layers=3, num_attention_heads=4,
        num_key_value_heads=2, intermediate_size=128, max_position_embeddings=128,
        rms_norm_eps=1e-6, rope_theta=10000.0, tie_word_embeddings=True,
        attn_implementation="eager",
    )
    model = Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg)
    params = params_from_state_dict(cfg, model.state_dict())
    ids = np.random.default_rng(4).integers(0, 256, size=(1, 20))
    return cfg, params, model, ids


def test_uniform_mul_rule():
    a, b = jnp.asarray([2.0, 3.0]), jnp.asarray([5.0, 7.0])
    np.testing.assert_allclose(np.asarray(uniform_mul(a, b)), [10.0, 21.0])
    ga, gb = jax.grad(lambda a, b: jnp.sum(uniform_mul(a, b)), argnums=(0, 1))(a, b)
    np.testing.assert_allclose(np.asarray(ga), [2.5, 3.5])  # 0.5 * b
    np.testing.assert_allclose(np.asarray(gb), [1.0, 1.5])  # 0.5 * a


def test_lrp_forward_logits_match_standard_forward(qwen_setup):
    """With zero offsets the LRP forward's primal equals the normal forward."""
    from edgellm_tpu.models import forward

    cfg, params, _, ids = qwen_setup
    L, S = cfg.num_layers, ids.shape[1]
    off = jnp.zeros((L, 1, cfg.num_heads, S, S))
    lrp_logits, probs = lrp_forward(cfg, params, jnp.asarray(ids), off)
    base_logits, _ = forward(cfg, params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(lrp_logits), np.asarray(base_logits),
                               atol=1e-4, rtol=1e-4)
    # probs rows sum to 1
    np.testing.assert_allclose(np.asarray(probs.sum(-1)), 1.0, atol=1e-5)


@pytest.mark.parametrize("batch", [1, 2])
def test_head_relevance_matches_torch_lrp_oracle(qwen_setup, batch):
    cfg, params, model, _ = qwen_setup
    ids = np.random.default_rng(4).integers(0, 256, size=(batch, 20))
    got = np.asarray(_chunk_relevance(cfg)(params, jnp.asarray(ids)))
    want = _torch_lrp_relevance(model, torch.tensor(ids))
    assert got.shape == want.shape == (3, 4)
    np.testing.assert_allclose(got, want, atol=1e-3, rtol=1e-3)


def test_run_relevance_extraction_normalized(qwen_setup):
    cfg, params, _, _ = qwen_setup
    corpus = np.random.default_rng(9).integers(0, 256, 80)
    w = run_relevance_extraction(cfg, params, corpus, max_length=32, stride=16,
                                 max_chunks=3)
    assert w.shape == (cfg.num_layers, cfg.num_heads)
    np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-6)


def test_window_batched_relevance_matches_unbatched(qwen_setup):
    """Relevance is a plain sum over windows, so batching them is exact up to
    fp32 in-batch summation order."""
    cfg, params, _, _ = qwen_setup
    corpus = np.random.default_rng(11).integers(0, 256, 150)
    stats_b: dict = {}
    want = run_relevance_extraction(cfg, params, corpus, max_length=32, stride=16)
    got = run_relevance_extraction(cfg, params, corpus, max_length=32, stride=16,
                                   window_batch=4, stats=stats_b)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-4)
    assert stats_b["chunks"] > 0 and stats_b["it_per_s"] > 0


def test_relevance_checkpoint_resume(qwen_setup, tmp_path):
    cfg, params, _, _ = qwen_setup
    corpus = np.random.default_rng(12).integers(0, 256, 150)
    kw = dict(max_length=32, stride=16, window_batch=2)
    want = run_relevance_extraction(cfg, params, corpus, **kw)

    ckpt = str(tmp_path / "rel_ckpt.json")
    metrics = str(tmp_path / "rel_metrics.jsonl")
    run_relevance_extraction(cfg, params, corpus, max_chunks=4,
                             checkpoint_path=ckpt, checkpoint_every=2,
                             metrics_path=metrics, **kw)
    got = run_relevance_extraction(cfg, params, corpus, checkpoint_path=ckpt,
                                   checkpoint_every=2, metrics_path=metrics, **kw)
    np.testing.assert_allclose(got, want, atol=1e-6, rtol=1e-6)
    import json
    lines = [json.loads(l) for l in open(metrics)]
    assert lines[-1]["final"] and lines[-1]["it_per_s"] > 0


def test_relevance_with_bf16_params(qwen_setup):
    """The bench runs relevance on a bf16 param pytree; the fp32-pinned LRP
    stream must accept it without the scan-carry dtype mismatch that bf16
    params once triggered. No closeness-to-fp32 claim: the vjp seed selects
    the ARGMAX last-position logit, which can flip token under bf16-rounded
    weights — relevance is discontinuous in the weights by construction."""
    cfg, params, _, _ = qwen_setup
    corpus = np.random.default_rng(13).integers(0, 256, 100)
    bf16 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, params)
    wbf = run_relevance_extraction(cfg, bf16, corpus, max_length=32, stride=16,
                                   window_batch=2)
    assert np.isfinite(wbf).all()
    np.testing.assert_allclose(wbf.sum(axis=1), 1.0, atol=1e-6)
