"""Worker for the REAL 2-process distributed test (test_distributed.py).

Run as a subprocess (one per process rank) with JAX_PLATFORMS=cpu and
XLA_FLAGS=--xla_force_host_platform_device_count=2 in the environment:

    python tests/multiproc_worker.py <rank> <nprocs> <port> <out_dir> [max_chunks]

Joins a localhost coordinator via the package's own ``initialize_distributed``,
builds the slice-aware multi-host stage mesh (data axis spanning the two
processes), and runs a tiny split eval whose per-example NLLs are sharded
across processes — executing, not mocking, ``fetch_global``'s
``process_allgather`` branch and the process-0-only checkpoint writes. Every
rank writes its final result dict to ``out_dir/result_<rank>.json``; under
SPMD all ranks must agree, and the parent test compares rank files to each
other and to a single-process run.
"""
import json
import os
import sys

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
# cross-process CPU collectives (the ICI/DCN analogue in this test rig)
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("EDGELLM_JAX_CACHE",
                   os.path.join(os.path.dirname(__file__), ".jax_cache")))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def workload():
    """The shared tiny split-eval workload: (cfg_kwargs, corpus_seed_len,
    run_split_eval kwargs). One definition for both the subprocess workers and
    the parent test's single-process oracle, so they cannot drift."""
    cfg_kwargs = dict(num_layers=4, hidden_size=32, num_heads=4, vocab_size=128)
    run_kwargs = dict(cuts=(1,), hop_codecs=("int4_per_token",), max_length=16,
                      stride=8, time_hops=False)
    return cfg_kwargs, (7, 16 + 8 * 6), run_kwargs


def main():
    rank, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    out_dir = sys.argv[4]
    max_chunks = int(sys.argv[5]) if len(sys.argv) > 5 else None

    from edgellm_tpu.parallel import (initialize_distributed,
                                      make_multihost_stage_mesh)

    n = initialize_distributed(coordinator_address=f"localhost:{port}",
                               num_processes=nprocs, process_id=rank)
    assert n == nprocs, f"expected {nprocs} processes, initialize returned {n}"
    assert jax.process_count() == nprocs
    assert len(jax.devices()) == nprocs * len(jax.local_devices())

    from edgellm_tpu.models import tiny_config, init_params
    from edgellm_tpu.eval.split_eval import run_split_eval

    # stage axis within a process, data axis across the two processes
    mesh = make_multihost_stage_mesh(2, n_data=nprocs, n_model=1)
    by_proc = {d.process_index for d in
               np.asarray(mesh.devices)[:, 0, :].ravel()}
    assert len(by_proc) == 1, "a stage group spans processes"

    cfg_kwargs, (seed, length), run_kwargs = workload()
    cfg = tiny_config("qwen2", **cfg_kwargs)
    params = init_params(cfg, jax.random.key(0))  # identical on every rank
    corpus = np.random.default_rng(seed).integers(0, cfg.vocab_size, length)

    result = run_split_eval(
        cfg, params, corpus, mesh=mesh, window_batch=nprocs,
        max_chunks=max_chunks,
        checkpoint_path=os.path.join(out_dir, "ckpt.json"),
        checkpoint_every=1,
        metrics_path=os.path.join(out_dir, "metrics.jsonl"), **run_kwargs)

    with open(os.path.join(out_dir, f"result_{rank}.json"), "w") as f:
        json.dump({k: v for k, v in result.items()
                   if isinstance(v, (int, float, str, list))}, f)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
