"""Speculative split decode: stage-0 draft, k-token batched verify (PR 11).

The headline contract is LOSSLESS acceptance: at temperature 0 every token a
speculative ``generate_split`` emits is the argmax the vanilla loop would
have produced — token-identical on the same seed/plan at any k, because the
accept rule emits the verify pass's own argmax whether or not the draft
agreed. Also covered here:

- ``verify_step`` logits == k sequential ``decode_step`` logits (the one
  quantized (1, k, D) boundary block carries the same information as k
  single-token hops);
- the verify wire-byte contract: one burst's hop bytes == k x the
  single-token hop bytes (the fused +8-byte seal is graphlint's half);
- kill-between-draft-and-verify checkpoint/resume: the resumed stream is
  token-identical to the uninterrupted run at k in {1, 4, 8} (burst
  boundaries depend only on the committed prefix);
- jit discipline: one draft executable and one verify executable per
  (capacity, k) — the second same-shape run compiles nothing;
- a disabled SpecConfig is pure host-side dispatch (no verify executables
  built, vanilla tokens out), and the ``run.py`` params validator accepts
  the shipped spec config while refusing the documented foot-guns;
- greedy identity survives a faulty boundary wire when retries are allowed
  to recover corrupt payloads (substitution would legitimately diverge).
"""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.codecs.faults import FaultConfig, LinkPolicy
from edgellm_tpu.models import init_params, tiny_config
from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh
from edgellm_tpu.serve import (CheckpointError, RecoveryConfig,
                               generate_split, resume_split)
from edgellm_tpu.serve.speculative import (MAX_SPEC_K, SpecConfig,
                                           draft_from_params,
                                           generate_speculative,
                                           spec_capacity)

CFG = tiny_config("qwen2", num_layers=6, hidden_size=32, num_heads=4,
                  vocab_size=128)
SPLIT = SplitConfig(cuts=(2,), hop_codecs=("int8_per_token",))
PROMPT, MAX_NEW = 10, 9
KS = [1, 4, 8]
#: one shared capacity, big enough for the widest verify window, so the
#: vanilla and every spec leg trace against the same cache geometry
CAP = spec_capacity(PROMPT, MAX_NEW, max(KS))


def _ids(batch=1, seed=11):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (batch, PROMPT)))


@pytest.fixture(scope="module")
def setup():
    params = init_params(CFG, jax.random.key(1))
    rt = SplitRuntime(CFG, SPLIT, make_stage_mesh(2))
    placed = rt.place_params(params)
    ids = _ids()
    vanilla = np.asarray(generate_split(rt, placed, ids, MAX_NEW,
                                        capacity=CAP))
    return dict(params=params, rt=rt, placed=placed, ids=ids,
                vanilla=vanilla)


# ---------------------------------------------------------------------------
# lossless greedy acceptance: token-identical to vanilla at every k
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", KS)
def test_greedy_token_identical_to_vanilla(setup, k):
    s = setup
    stats = {}
    toks = generate_split(s["rt"], s["placed"], s["ids"], MAX_NEW,
                          capacity=CAP, speculative=SpecConfig(k=k),
                          raw_params=s["params"], stats=stats)
    assert toks.shape == (1, MAX_NEW)
    assert np.array_equal(np.asarray(toks), s["vanilla"])
    sp = stats["speculative"]
    assert sp["k"] == k
    assert sp["bursts"] >= 1
    # every burst is one boundary round-trip for 1..k emitted tokens
    assert 0.0 < sp["hops_per_token"] <= 1.0
    if k == 1:
        # the degenerate window drafts nothing and must cost exactly the
        # vanilla one-hop-per-token rate
        assert sp["drafted"] == 0
        assert sp["hops_per_token"] == 1.0


def test_spec_stats_account_every_draft(setup):
    s = setup
    stats = {}
    generate_split(s["rt"], s["placed"], s["ids"], MAX_NEW, capacity=CAP,
                   speculative=SpecConfig(k=4), raw_params=s["params"],
                   stats=stats)
    sp = stats["speculative"]
    assert sp["accepted"] + sp["rejected"] == sp["drafted"]
    assert sp["drafted"] == sp["bursts"] * 3  # k-1 drafts per burst
    assert sp["acceptance_rate"] == pytest.approx(
        sp["accepted"] / sp["drafted"] if sp["drafted"] else 0.0)
    assert stats["decode_steps"] == MAX_NEW - 1  # emitted after token 0


def test_temperature_sampling_runs_with_spec_stats(setup):
    """temperature > 0 uses residual resampling — distribution-identical,
    not bitwise, so assert shape/range and the bookkeeping, not parity."""
    s = setup
    stats = {}
    toks = generate_split(s["rt"], s["placed"], s["ids"], MAX_NEW,
                          capacity=CAP, temperature=0.8,
                          rng_key=jax.random.key(5),
                          speculative=SpecConfig(k=4),
                          raw_params=s["params"], stats=stats)
    out = np.asarray(toks)
    assert out.shape == (1, MAX_NEW)
    assert (0 <= out).all() and (out < CFG.vocab_size).all()
    assert stats["speculative"]["bursts"] >= 1


# ---------------------------------------------------------------------------
# the verify pass itself: k positions in one hop == k single-token steps
# ---------------------------------------------------------------------------


def test_verify_step_matches_stepwise_decode(setup):
    s = setup
    rt, placed, ids = s["rt"], s["placed"], s["ids"]
    k = 4
    rng = np.random.default_rng(3)
    feed = rng.integers(0, CFG.vocab_size, (k,))

    _, cache_a = rt.prefill_decode(placed, ids, CAP)
    step_logits = []
    for t in feed:
        logits, cache_a = rt.decode_step(placed, cache_a,
                                         jnp.asarray([t], jnp.int32))
        step_logits.append(np.asarray(logits))

    _, cache_b = rt.prefill_decode(placed, ids, CAP)
    vlogits, cache_b = rt.verify_step(placed, cache_b,
                                      jnp.asarray(feed[None, :], jnp.int32))
    assert vlogits.shape == (1, k, CFG.vocab_size)
    assert int(cache_b["length"]) == PROMPT + k
    for j in range(k):
        np.testing.assert_allclose(np.asarray(vlogits[:, j]), step_logits[j],
                                   atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("k", KS)
def test_verify_hop_bytes_scale_linearly(setup, k):
    """ONE verify burst moves exactly k single-token payloads' worth of
    bytes per hop — the amortization claim is in round-trips, not bytes
    (the fused-mode k x hop_bytes + 8 framing is checked by graphlint's
    split.verify_step.fused contract)."""
    rt = setup["rt"]
    (per_burst,) = rt.verify_hop_bytes(1, k)
    (per_step,) = rt.decode_hop_bytes(1)
    assert per_burst == k * per_step


def test_jit_miss_free_after_first_burst(setup):
    """Second same-shape run compiles nothing: the fill level rides as a
    traced scalar through one draft executable and one verify executable
    per (capacity, k)."""
    s = setup
    spec = SpecConfig(k=4)
    kw = dict(capacity=CAP, speculative=spec, raw_params=s["params"])
    generate_split(s["rt"], s["placed"], s["ids"], MAX_NEW, **kw)  # warm
    n_verify = len(s["rt"]._verify_fns_cache)
    stats = {}
    generate_split(s["rt"], s["placed"], s["ids"], MAX_NEW, stats=stats, **kw)
    assert stats["speculative"]["draft_step_cache_misses"] == 0
    assert len(s["rt"]._verify_fns_cache) == n_verify


def test_disabled_spec_is_pure_dispatch(setup):
    """SpecConfig(enabled=False) must run the vanilla loop untouched: same
    tokens, and the runtime never builds a verify executable (the jaxpr
    half of this contract — fingerprint identity — is graphlint's
    split.decode_step.spec-disabled-identity check)."""
    s = setup
    rt2 = SplitRuntime(CFG, SPLIT, make_stage_mesh(2))
    placed2 = rt2.place_params(s["params"])
    toks = generate_split(rt2, placed2, s["ids"], MAX_NEW, capacity=CAP,
                          speculative=SpecConfig(enabled=False, k=4),
                          raw_params=s["params"])
    assert np.array_equal(np.asarray(toks), s["vanilla"])
    assert len(rt2._verify_fns_cache) == 0


# ---------------------------------------------------------------------------
# config / argument validation
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kwargs, msg", [
    ({"k": 0}, "k must be in"),
    ({"k": MAX_SPEC_K + 1}, "k must be in"),
    ({"k": True}, "k must be an int"),
    ({"k": "4"}, "k must be an int"),
    ({"draft_source": "ngram"}, "unknown draft_source"),
    ({"draft_layers": 0}, "draft_layers must be"),
    ({"draft_layers": False}, "draft_layers must be"),
])
def test_spec_config_rejects_bad_fields(kwargs, msg):
    with pytest.raises(ValueError, match=msg):
        SpecConfig(**kwargs)


def test_draft_layers_bounded_by_stage0(setup):
    """The draft must run hop-free on stage 0: draft_layers is capped at
    cut + 1 layers, and defaults to exactly that."""
    params = setup["params"]
    cut = SPLIT.cuts[0]
    dcfg, dparams = draft_from_params(CFG, params, SpecConfig(), cut=cut)
    assert dcfg.num_layers == cut + 1
    assert jax.tree_util.tree_leaves(dparams["layers"])[0].shape[0] == cut + 1
    with pytest.raises(ValueError, match="stage 0 owns"):
        draft_from_params(CFG, params, SpecConfig(draft_layers=cut + 2),
                          cut=cut)


def test_generate_speculative_guards(setup):
    s = setup
    spec = SpecConfig(k=4)
    with pytest.raises(ValueError, match="enabled"):
        generate_speculative(s["rt"], s["placed"], s["ids"], MAX_NEW,
                             spec=SpecConfig(enabled=False),
                             raw_params=s["params"])
    with pytest.raises(ValueError, match="raw_params"):
        generate_speculative(s["rt"], s["placed"], s["ids"], MAX_NEW,
                             spec=spec)
    with pytest.raises(ValueError, match="batch"):
        generate_speculative(s["rt"], s["placed"], _ids(batch=2), MAX_NEW,
                             spec=spec, raw_params=s["params"])
    with pytest.raises(ValueError, match="cache overflow"):
        generate_speculative(s["rt"], s["placed"], s["ids"], MAX_NEW,
                             spec=spec, raw_params=s["params"],
                             capacity=PROMPT + MAX_NEW)
    from edgellm_tpu.serve.recovery import StageFailure
    with pytest.raises(ValueError, match="failover drills"):
        generate_speculative(
            s["rt"], s["placed"], s["ids"], MAX_NEW, spec=spec,
            raw_params=s["params"],
            recovery=RecoveryConfig(stage_failure=StageFailure(stage=1,
                                                               at_step=2)))


def test_spec_capacity_math():
    assert spec_capacity(10, 9, 1) == 19
    assert spec_capacity(10, 9, 4) == 21  # last burst writes k-2 rows past
    assert spec_capacity(10, 9, 8) == 25


# ---------------------------------------------------------------------------
# checkpoint / resume: kill between draft and verify, resume, same stream
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", KS)
def test_kill_between_draft_and_verify_resume_identical(setup, tmp_path, k):
    """The ISSUE's mid-verify-burst drill: the process dies AFTER the draft
    proposed its tokens but BEFORE the verify hop committed anything. The
    checkpoint on disk is the last burst boundary; the resumed run must
    re-draft from the committed prefix and emit the exact uninterrupted
    stream (which at temperature 0 is the vanilla stream)."""
    s = setup
    rt = SplitRuntime(CFG, SPLIT, make_stage_mesh(2))  # isolated: patched
    placed = rt.place_params(s["params"])
    spec = SpecConfig(k=k)
    ckpt = str(tmp_path / f"spec_{k}.ckpt")
    # 0-indexed verify call to kill: a run has at least ceil(8/k) bursts
    # (full acceptance emits k per burst), so this is always reached; at
    # k=8 the very first verify dies and resume starts from the prefill
    # checkpoint (token 0 only)
    fail_at = {1: 2, 4: 1, 8: 0}[k]
    orig = rt.verify_step
    calls = {"n": 0}

    def dying_verify(placed_params, cache, token_ids):
        if calls["n"] == fail_at:
            raise RuntimeError("simulated kill between draft and verify")
        calls["n"] += 1
        return orig(placed_params, cache, token_ids)

    rt.verify_step = dying_verify
    try:
        with pytest.raises(RuntimeError, match="simulated kill"):
            generate_split(rt, placed, s["ids"], MAX_NEW, capacity=CAP,
                           speculative=spec, raw_params=s["params"],
                           recovery=RecoveryConfig(checkpoint_path=ckpt,
                                                   checkpoint_every=1))
    finally:
        rt.verify_step = orig
    assert os.path.exists(ckpt)

    rstats = {}
    full = resume_split(rt, placed, ckpt, speculative=spec,
                        raw_params=s["params"], stats=rstats)
    assert rstats["resumed_from_step"] < MAX_NEW - 1
    assert rstats["recovery_counters"]["resume_ok"] == 1
    assert np.array_equal(np.asarray(full), s["vanilla"])


def test_resume_refuses_spec_window_mismatch(setup, tmp_path):
    s = setup
    ckpt = str(tmp_path / "spec.ckpt")
    stats = {}
    generate_split(s["rt"], s["placed"], s["ids"], MAX_NEW, capacity=CAP,
                   speculative=SpecConfig(k=4), raw_params=s["params"],
                   recovery=RecoveryConfig(checkpoint_path=ckpt,
                                           halt_at_step=3),
                   stats=stats)
    assert stats["halted_at_step"] >= 3  # halts on the next burst boundary
    with pytest.raises(CheckpointError, match="speculative"):
        resume_split(s["rt"], s["placed"], ckpt, speculative=SpecConfig(k=8),
                     raw_params=s["params"])
    # the matching window resumes to the full vanilla stream
    full = resume_split(s["rt"], s["placed"], ckpt, speculative=SpecConfig(k=4),
                        raw_params=s["params"])
    assert np.array_equal(np.asarray(full), s["vanilla"])


# ---------------------------------------------------------------------------
# faulty boundary wire: greedy identity survives when retries recover
# ---------------------------------------------------------------------------


def test_greedy_identity_on_retrying_faulty_link(setup):
    """Corrupt verify payloads retried to recovery leave the accepted tokens
    untouched — the spec loop rides the sealed/verified hop ladder
    unchanged. (A substitute-on-fail policy would legitimately diverge:
    vanilla and spec see different fault streams.)"""
    s = setup
    faults = FaultConfig(bitflip_rate=2e-4, seed=3)
    policy = LinkPolicy(max_retries=4)
    rt_f = SplitRuntime(CFG, SPLIT, make_stage_mesh(2), faults=faults,
                        policy=policy)
    placed_f = rt_f.place_params(s["params"])
    vanilla = np.asarray(generate_split(rt_f, placed_f, s["ids"], MAX_NEW,
                                        capacity=CAP))
    stats = {}
    toks = generate_split(rt_f, placed_f, s["ids"], MAX_NEW, capacity=CAP,
                          speculative=SpecConfig(k=4),
                          raw_params=s["params"], stats=stats)
    assert np.array_equal(np.asarray(toks), vanilla)
    # spec made fewer boundary round-trips than the vanilla leg
    assert stats["link_counters"]["hops"][0] < MAX_NEW


# ---------------------------------------------------------------------------
# run.py params validation: the shipped config and the refusals
# ---------------------------------------------------------------------------


def _spec_params():
    root = os.path.join(os.path.dirname(__file__), "..")
    with open(os.path.join(root, "configs", "split11_qwen_spec.json")) as f:
        return json.load(f)


def test_params_validation_accepts_spec_config():
    from edgellm_tpu.run import _validate_params_json

    _validate_params_json(_spec_params())  # must not raise


@pytest.mark.parametrize("patch, msg", [
    ({"experiment": "split", "max_length": 64, "stride": 32},
     "only applies to experiment 'serve'"),
    ({"cuts": None}, "add 'cuts'"),
    ({"speculative": [4]}, "object of SpecConfig fields"),
    ({"speculative": {"k": 4, "window": 2}}, "unknown field"),
    ({"speculative": {"k": 0}}, "k must be in"),
    ({"speculative": {"k": 4, "draft_source": "ngram"}}, "draft_source"),
    ({"fused_hops": "remote"}, "unprobed"),
    ({"batching": {"page_size": 8, "num_pages": 17, "max_slots": 4,
                   "pages_per_slot": 4}}, "drop"),
])
def test_params_validation_rejects_spec_footguns(patch, msg):
    from edgellm_tpu.run import _validate_params_json

    p = _spec_params()
    p.update(patch)
    if p.get("cuts") is None:
        p.pop("cuts", None)
        p.pop("hop_codecs", None)
    with pytest.raises(SystemExit, match=msg):
        _validate_params_json(p)


def test_params_validation_disabled_spec_allows_batching():
    from edgellm_tpu.run import _validate_params_json

    p = _spec_params()
    p["speculative"] = {"enabled": False, "k": 4}
    p["batching"] = {"page_size": 8, "num_pages": 17, "max_slots": 4,
                     "pages_per_slot": 4}
    _validate_params_json(p)  # must not raise


def test_soak_identity_replay_uses_the_spec_loop():
    """A speculative front soaked at temperature > 0 must still pass the
    soak's bit-identical replay: residual resampling draws a different
    stream than vanilla sampling, so the reference must run the same spec
    loop (with the front's capacity bump) — not the vanilla one."""
    from edgellm_tpu.serve import ServeFront
    from edgellm_tpu.serve.soak import SoakConfig, run_soak
    from edgellm_tpu.utils.clock import FakeClock

    params = init_params(CFG, jax.random.key(1))
    rt = SplitRuntime(CFG, SPLIT, make_stage_mesh(2))
    clk = FakeClock()
    front = ServeFront(CFG, params, split_runtime=rt,
                       speculative=SpecConfig(k=4), clock=clk)
    soak = SoakConfig(n_requests=3, arrival_rate=1.0, prompt_len=8,
                      max_new_tokens=6, deadline_s=120.0)
    art = run_soak(front, soak, clock=clk)
    assert art["outcomes"].get("completed") == 3
    identity = art["token_identity"]
    assert identity["checked"] == 3 and identity["ok"], identity
