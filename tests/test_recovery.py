"""Survivable split decode: checkpoints, stage failover, watchdogs (PR 3).

Correctness anchors, in order of importance:

- kill-and-resume is TOKEN-IDENTICAL: a generation halted at step k with a
  :class:`DecodeCheckpoint` and resumed from disk emits the exact token
  matrix of the uninterrupted same-seed run, for k at the first, a middle,
  and the last decode step (the checkpoint restores the KV cache, position
  offsets, RNG key, and sampled prefix bit-exactly — no recompute);
- a whole-stage loss mid-decode completes the generation on a re-planned
  boundary with non-zero failover counters, and — with lossless hops — the
  output matches the clean run exactly (the prefix re-prefill reproduces
  what the dead pipeline would have computed);
- the zero-recovery config builds the exact pre-recovery graph: enabling
  ``recovery=RecoveryConfig()`` with every feature off changes nothing,
  bit for bit;
- checkpoint I/O is self-verifying: bit-exact round-trips per dtype, and
  truncation/corruption/foreign files die with a typed
  :class:`CheckpointError` naming the problem;
- the watchdog fires deterministically on an injected fake clock, in both
  the decode loop and the eval harness.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.models import tiny_config, init_params
from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh
from edgellm_tpu.serve import (CheckpointError, DecodeCheckpoint,
                               DecodeTimeout, LocalRuntime, RecoveryConfig,
                               StageFailure, StageLostError, Watchdog,
                               generate, generate_split, resume_split)
from edgellm_tpu.utils.clock import sequence_clock

SPLIT_CFG = tiny_config("qwen2", num_layers=6, hidden_size=32, num_heads=4,
                        vocab_size=128)
MAX_NEW = 8
TEMP = 0.7


def _ids(cfg, batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, size=(batch, seq)))


@pytest.fixture(scope="module")
def setup():
    params = init_params(SPLIT_CFG, jax.random.key(1))
    ids = _ids(SPLIT_CFG, 2, 14, seed=21)
    split = SplitConfig(cuts=(2,), hop_codecs=("fp32",))
    rt = SplitRuntime(SPLIT_CFG, split, make_stage_mesh(2))
    placed = rt.place_params(params)
    key = jax.random.key(7)
    clean = generate_split(rt, placed, ids, MAX_NEW, temperature=TEMP,
                           rng_key=key)
    return dict(params=params, ids=ids, split=split, rt=rt, placed=placed,
                key=key, clean=np.asarray(clean))


# ---------------------------------------------------------------------------
# checkpoint container: bit-exact round trip, typed failures
# ---------------------------------------------------------------------------


def test_checkpoint_roundtrip_bit_exact(tmp_path):
    import ml_dtypes

    rng = np.random.default_rng(0)
    arrays = {
        "f32": rng.standard_normal((3, 5)).astype(np.float32),
        "bf16": rng.standard_normal((2, 4, 4)).astype(ml_dtypes.bfloat16),
        "i32": rng.integers(-1000, 1000, (7,), dtype=np.int32),
        "u32": rng.integers(0, 2**32, (2, 2), dtype=np.uint32),
        "scalar": np.int32(42),
    }
    meta = {"step": 3, "nested": {"cuts": [2], "temperature": 0.7}}
    path = str(tmp_path / "ck.bin")
    DecodeCheckpoint(arrays, meta).save(path)
    assert not os.path.exists(path + ".part")  # atomic rename, no debris
    ck = DecodeCheckpoint.load(path)
    assert ck.meta == meta
    assert set(ck.arrays) == set(arrays)
    for name, a in arrays.items():
        b = ck.arrays[name]
        assert b.dtype == np.asarray(a).dtype and b.shape == np.asarray(a).shape
        assert np.asarray(a).tobytes() == b.tobytes(), name  # bit-exact


def test_checkpoint_truncated_raises(tmp_path):
    path = str(tmp_path / "ck.bin")
    DecodeCheckpoint({"a": np.arange(100, dtype=np.float32)}, {}).save(path)
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[: len(blob) // 2])
    with pytest.raises(CheckpointError, match="truncated"):
        DecodeCheckpoint.load(path)
    open(path, "wb").write(blob[:8])  # shorter than the fixed header
    with pytest.raises(CheckpointError, match="truncated"):
        DecodeCheckpoint.load(path)


def test_checkpoint_corrupted_raises(tmp_path):
    path = str(tmp_path / "ck.bin")
    DecodeCheckpoint({"a": np.arange(100, dtype=np.float32)}, {}).save(path)
    blob = bytearray(open(path, "rb").read())
    blob[-5] ^= 0xFF  # flip payload bits; length still matches
    open(path, "wb").write(bytes(blob))
    with pytest.raises(CheckpointError, match="CRC32|corrupted"):
        DecodeCheckpoint.load(path)


def test_checkpoint_bad_magic_and_missing(tmp_path):
    path = str(tmp_path / "notack.bin")
    open(path, "wb").write(b"\x00" * 64)
    with pytest.raises(CheckpointError, match="magic"):
        DecodeCheckpoint.load(path)
    with pytest.raises(CheckpointError, match="cannot read"):
        DecodeCheckpoint.load(str(tmp_path / "does_not_exist.bin"))


# ---------------------------------------------------------------------------
# kill-and-resume: token-identical at first/mid/last step
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [0, 3, MAX_NEW - 1])
def test_kill_and_resume_token_identical(setup, tmp_path, k):
    s = setup
    ckpt = str(tmp_path / "gen.ckpt")
    stats = {}
    part = generate_split(
        s["rt"], s["placed"], s["ids"], MAX_NEW, temperature=TEMP,
        rng_key=s["key"],
        recovery=RecoveryConfig(checkpoint_path=ckpt, halt_at_step=k),
        stats=stats)
    assert stats["halted_at_step"] == k
    assert part.shape == (2, k + 1)
    assert np.array_equal(np.asarray(part), s["clean"][:, : k + 1])
    rstats = {}
    full = resume_split(s["rt"], s["placed"], ckpt, stats=rstats)
    assert rstats["resumed_from_step"] == k
    assert rstats["recovery_counters"]["resume_ok"] == 1
    assert np.array_equal(np.asarray(full), s["clean"])  # token-identical


def test_resume_refuses_mismatched_plan(setup, tmp_path):
    s = setup
    ckpt = str(tmp_path / "gen.ckpt")
    generate_split(s["rt"], s["placed"], s["ids"], MAX_NEW, temperature=TEMP,
                   rng_key=s["key"],
                   recovery=RecoveryConfig(checkpoint_path=ckpt,
                                           halt_at_step=2))
    other = SplitRuntime(SPLIT_CFG,
                         SplitConfig(cuts=(4,), hop_codecs=("fp32",)),
                         make_stage_mesh(2))
    with pytest.raises(CheckpointError, match="split cuts"):
        resume_split(other, other.place_params(s["params"]), ckpt)


def test_periodic_checkpoints_written(setup, tmp_path):
    s = setup
    ckpt = str(tmp_path / "gen.ckpt")
    stats = {}
    out = generate_split(s["rt"], s["placed"], s["ids"], MAX_NEW,
                         temperature=TEMP, rng_key=s["key"],
                         recovery=RecoveryConfig(checkpoint_path=ckpt,
                                                 checkpoint_every=2),
                         stats=stats)
    assert np.array_equal(np.asarray(out), s["clean"])
    assert stats["recovery_counters"]["checkpoints_written"] >= 3
    # the last periodic write lands at step 6; resuming it replays the tail
    full = resume_split(s["rt"], s["placed"], ckpt)
    assert np.array_equal(np.asarray(full), s["clean"])


# ---------------------------------------------------------------------------
# stage failure + failover re-planning
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("at_step", [0, 3, MAX_NEW - 1])
def test_stage_failure_fails_over_and_completes(setup, at_step):
    s = setup
    # 3 stages so the failover re-plans onto a REAL 2-stage split
    rt3 = SplitRuntime(SPLIT_CFG,
                       SplitConfig(cuts=(1, 3), hop_codecs=("fp32", "fp32")),
                       make_stage_mesh(3))
    stats = {}
    out = generate_split(rt3, rt3.place_params(s["params"]), s["ids"],
                         MAX_NEW, temperature=TEMP, rng_key=s["key"],
                         recovery=RecoveryConfig(
                             stage_failure=StageFailure(stage=2,
                                                        at_step=at_step)),
                         raw_params=s["params"], stats=stats)
    rc = stats["recovery_counters"]
    assert rc["failovers"] == 1 and rc["replans"] == 1
    assert rc["recompute_tokens"] > 0
    # lossless hops: the re-planned run must match the clean output exactly
    assert np.array_equal(np.asarray(out), s["clean"])


def test_stage_failure_to_single_survivor_uses_local_runtime(setup):
    s = setup
    rt2 = SplitRuntime(SPLIT_CFG, s["split"], make_stage_mesh(2))
    stats = {}
    out = generate_split(rt2, rt2.place_params(s["params"]), s["ids"],
                         MAX_NEW, temperature=TEMP, rng_key=s["key"],
                         recovery=RecoveryConfig(
                             stage_failure=StageFailure(stage=0, at_step=2)),
                         raw_params=s["params"], stats=stats)
    assert stats["recovery_counters"]["failovers"] == 1
    assert np.array_equal(np.asarray(out), s["clean"])


def test_stage_failure_without_raw_params_raises(setup):
    s = setup
    rt2 = SplitRuntime(SPLIT_CFG, s["split"], make_stage_mesh(2))
    with pytest.raises(ValueError, match="raw_params"):
        generate_split(rt2, rt2.place_params(s["params"]), s["ids"], MAX_NEW,
                       recovery=RecoveryConfig(
                           stage_failure=StageFailure(stage=1, at_step=1)))


def test_stage_failure_replan_disabled_is_fatal(setup):
    s = setup
    rt2 = SplitRuntime(SPLIT_CFG, s["split"], make_stage_mesh(2))
    with pytest.raises(StageLostError):
        generate_split(rt2, rt2.place_params(s["params"]), s["ids"], MAX_NEW,
                       recovery=RecoveryConfig(
                           stage_failure=StageFailure(stage=1, at_step=1),
                           replan=False),
                       raw_params=s["params"])


def test_split_config_replan():
    sc = SplitConfig(cuts=(1, 3), hop_codecs=("int8_per_token", "fp32"))
    re2 = sc.replan(num_layers=6, n_stages=2)
    assert re2.cuts == (2,)
    assert re2.hop_codecs == ("int8_per_token",)  # first hop's codec, uniform
    assert sc.replan(6, 1).cuts == ()
    assert sc.replan(6, 3, codec="fp32").hop_codecs == ("fp32", "fp32")
    with pytest.raises(ValueError, match="re-plan"):
        sc.replan(6, 7)
    with pytest.raises(ValueError, match="explicit codec"):
        SplitConfig(cuts=(), hop_codecs=()).replan(6, 3)


# ---------------------------------------------------------------------------
# zero-recovery config == exact pre-recovery graph
# ---------------------------------------------------------------------------


def test_zero_recovery_config_is_bit_identical(setup):
    s = setup
    out = generate_split(s["rt"], s["placed"], s["ids"], MAX_NEW,
                         temperature=TEMP, rng_key=s["key"],
                         recovery=RecoveryConfig())
    assert np.array_equal(np.asarray(out), s["clean"])


def test_local_generate_recovery_parity():
    cfg = tiny_config("qwen2", num_layers=3, hidden_size=32, num_heads=4,
                      vocab_size=128)
    params = init_params(cfg, jax.random.key(2))
    ids = _ids(cfg, 2, 10, seed=5)
    key = jax.random.key(9)
    ref = generate(cfg, params, ids, 5, temperature=0.5, rng_key=key)
    out = generate(cfg, params, ids, 5, temperature=0.5, rng_key=key,
                   recovery=RecoveryConfig())
    assert np.array_equal(np.asarray(ref), np.asarray(out))


def test_local_generate_halt_and_resume(tmp_path):
    cfg = tiny_config("qwen2", num_layers=3, hidden_size=32, num_heads=4,
                      vocab_size=128)
    params = init_params(cfg, jax.random.key(2))
    ids = _ids(cfg, 2, 10, seed=5)
    key = jax.random.key(9)
    ref = generate(cfg, params, ids, 6, temperature=0.5, rng_key=key)
    ckpt = str(tmp_path / "local.ckpt")
    generate(cfg, params, ids, 6, temperature=0.5, rng_key=key,
             recovery=RecoveryConfig(checkpoint_path=ckpt, halt_at_step=2))
    full = resume_split(LocalRuntime(cfg), params, ckpt)
    assert np.array_equal(np.asarray(full), np.asarray(ref))


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def test_watchdog_fires_deterministically():
    # each passing check reads the clock twice: once for elapsed, once to
    # re-arm
    clock = sequence_clock([0.0, 1.0, 2.0, 3.0, 3.5, 100.0])
    wd = Watchdog(5.0, clock=clock)
    wd.arm()           # armed at t=0
    wd.check()         # elapsed 1.0: within deadline, re-arms at t=2.0
    wd.check()         # elapsed 1.0: fine, re-arms at t=3.5
    with pytest.raises(DecodeTimeout, match="deadline"):
        wd.check()     # elapsed 96.5: expired


def test_watchdog_writes_best_effort_checkpoint():
    clock = sequence_clock([0.0, 100.0])
    wd = Watchdog(1.0, clock=clock)
    wd.arm()
    wrote = []
    with pytest.raises(DecodeTimeout):
        wd.check(lambda: wrote.append(1))
    assert wrote == [1]
    # a failing checkpoint sink must not mask the timeout
    clock2 = sequence_clock([0.0, 100.0])
    wd2 = Watchdog(1.0, clock=clock2)
    wd2.arm()
    with pytest.raises(DecodeTimeout):
        wd2.check(lambda: 1 / 0)


def test_decode_watchdog_fires_with_fake_clock(setup, tmp_path):
    s = setup
    ckpt = str(tmp_path / "wd.ckpt")
    with pytest.raises(DecodeTimeout):
        generate_split(s["rt"], s["placed"], s["ids"], MAX_NEW,
                       temperature=TEMP, rng_key=s["key"],
                       recovery=RecoveryConfig(
                           checkpoint_path=ckpt, deadline_s=1.0,
                           clock=sequence_clock(range(0, 100000, 100))))
    # the expiring check wrote a best-effort checkpoint we can resume from
    full = resume_split(s["rt"], s["placed"], ckpt)
    assert np.array_equal(np.asarray(full), s["clean"])


# ---------------------------------------------------------------------------
# eval harness threading
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def eval_setup():
    from edgellm_tpu.eval.split_eval import run_split_eval

    params = init_params(SPLIT_CFG, jax.random.key(1))
    toks = np.asarray(_ids(SPLIT_CFG, 1, 400, seed=3)).reshape(-1)
    base = run_split_eval(SPLIT_CFG, params, toks, cuts=[1, 3],
                          hop_codecs=["fp32", "fp32"], max_length=64,
                          stride=32, time_hops=False)
    return dict(params=params, toks=toks, base=base)


def test_eval_stage_failover_same_ppl(eval_setup):
    from edgellm_tpu.eval.split_eval import run_split_eval

    e = eval_setup
    res = run_split_eval(SPLIT_CFG, e["params"], e["toks"], cuts=[1, 3],
                         hop_codecs=["fp32", "fp32"], max_length=64,
                         stride=32, time_hops=False,
                         stage_failure={"stage": 2, "at_step": 2})
    rec = res["recovery"]
    assert rec["counters"]["failovers"] == 1
    assert rec["counters"]["replans"] == 1
    assert rec["replanned_cuts"] == [2]
    assert rec["failover_mesh"]["stage"] == 2
    assert res["chunks"] == e["base"]["chunks"]
    # lossless hops: the boundary's position cannot change the PPL
    assert res["ppl"] == pytest.approx(e["base"]["ppl"], abs=1e-9)
    # post-failover wire traffic is accounted per plan generation
    assert sum(rec["failover_hop_bytes_total"]["1"]) > 0


def test_eval_zero_recovery_parity(eval_setup):
    from edgellm_tpu.eval.split_eval import run_split_eval

    e = eval_setup
    res = run_split_eval(SPLIT_CFG, e["params"], e["toks"], cuts=[1, 3],
                         hop_codecs=["fp32", "fp32"], max_length=64,
                         stride=32, time_hops=False,
                         recovery={"replan": True, "max_failovers": 1})
    assert res["ppl"] == e["base"]["ppl"]
    assert res["measured_hop_bytes_total"] == \
        e["base"]["measured_hop_bytes_total"]


def test_eval_watchdog_fires_with_fake_clock(eval_setup):
    from edgellm_tpu.eval.split_eval import run_split_eval

    e = eval_setup
    with pytest.raises(DecodeTimeout):
        run_split_eval(SPLIT_CFG, e["params"], e["toks"], cuts=[1, 3],
                       hop_codecs=["fp32", "fp32"], max_length=64, stride=32,
                       time_hops=False, deadline_s=1.0,
                       _clock=sequence_clock(range(0, 1000000, 100)))


def test_eval_rejects_ring_stage_failure(eval_setup):
    from edgellm_tpu.eval.split_eval import run_split_eval

    e = eval_setup
    with pytest.raises(ValueError, match="n_seq"):
        run_split_eval(SPLIT_CFG, e["params"], e["toks"], cuts=[1],
                       hop_codecs=["int8_per_token"], max_length=64,
                       stride=32, n_seq=2,
                       stage_failure={"stage": 1, "at_step": 0})


# ---------------------------------------------------------------------------
# params.json validation
# ---------------------------------------------------------------------------


def test_params_validation_accepts_failover_config():
    import json

    from edgellm_tpu.run import _validate_params_json

    root = os.path.join(os.path.dirname(__file__), "..")
    with open(os.path.join(root, "configs", "split6_qwen_failover.json")) as f:
        _validate_params_json(json.load(f))  # must not raise


@pytest.mark.parametrize("patch, msg", [
    ({"deadline": -3}, "deadline"),
    ({"deadline": True}, "deadline"),
    ({"stage_failure": {"stage": 9, "at_step": 0}}, "out of range"),
    ({"stage_failure": {"stageX": 1}}, "unknown field"),
    ({"stage_failure": [1, 2]}, "stage_failure"),
    ({"recovery": {"max_failovers": 0}}, "max_failovers"),
    ({"recovery": {"replan": "yes"}}, "replan"),
    ({"recovery": {"bogus": 1}}, "unknown field"),
])
def test_params_validation_rejects_bad_recovery(patch, msg):
    from edgellm_tpu.run import _validate_params_json

    p = {"experiment": "split", "cuts": [1, 3],
         "hop_codecs": ["fp32", "fp32"], "max_length": 64, "stride": 32,
         **patch}
    with pytest.raises(SystemExit, match=msg):
        _validate_params_json(p)


def test_params_validation_recovery_keys_split_only():
    from edgellm_tpu.run import _validate_params_json

    with pytest.raises(SystemExit, match="only apply"):
        _validate_params_json({"experiment": "initial",
                               "layers_of_interest": [1], "ratios": [0.5],
                               "deadline": 10.0})
