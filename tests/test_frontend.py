"""Overload-robust serving front: admission, breakers, brownout, soak (PR 7).

Correctness anchors, in order of importance:

- a request the front reports ``completed`` is TOKEN-IDENTICAL to calling
  ``generate``/``generate_split`` directly with the same seed and the same
  (batch, capacity) plan — the front adds scheduling, never different math;
- the deterministic chaos soak survives a mid-soak stage kill: at least one
  request fails over onto the re-planned boundary, a post-kill recovery
  time is measured, every completed request still matches its fault-free
  reference, and total ladder retries stay inside the process-wide budget;
- circuit breakers walk closed -> open -> half-open -> closed on an
  injected fake clock, with failed probes re-opening the circuit;
- admission rejects are typed and recorded: a full queue and an infeasible
  deadline each name their reason without touching a device;
- the retry budget is process-wide back-pressure: once a forced-bad link
  drains it, the front refuses the faulted route instead of funding a
  retry storm (with fallback disabled, the request is rejected);
- brownout walks one level per dwell in BOTH directions — recovering load
  cannot flap the service back to full quality without re-earning it.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.codecs.faults import FaultConfig, LinkPolicy
from edgellm_tpu.models import init_params, tiny_config
from edgellm_tpu.parallel import SplitConfig, SplitRuntime, make_stage_mesh
from edgellm_tpu.serve import (AdmissionConfig, AdmissionController,
                               BreakerConfig, BrownoutConfig,
                               BrownoutController, CircuitBreaker,
                               DeadlineInfeasible, QueueFull, Request,
                               RetryBudgetConfig, ServeFront,
                               ServeFrontConfig, generate, generate_split)
from edgellm_tpu.serve.soak import SoakConfig, run_soak
from edgellm_tpu.utils.clock import FakeClock

CFG = tiny_config("qwen2", num_layers=6, hidden_size=32, num_heads=4,
                  vocab_size=128)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(1))


def _prompt(seed=3, batch=1, seq=8):
    rng = np.random.default_rng(seed)
    return rng.integers(0, CFG.vocab_size, (batch, seq)).astype(np.int32)


# ---------------------------------------------------------------------------
# circuit breaker: fake-clock state machine
# ---------------------------------------------------------------------------


def test_breaker_full_cycle_on_fake_clock():
    clk = FakeClock()
    br = CircuitBreaker("x", BreakerConfig(failure_threshold=3,
                                           reset_timeout_s=10.0,
                                           half_open_probes=1), clock=clk)
    assert br.state == "closed" and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"  # under threshold
    br.record_failure()
    assert br.state == "open" and not br.allow()
    clk.advance(9.9)
    assert br.state == "open"  # timeout not elapsed
    clk.advance(0.2)
    assert br.state == "half_open"
    assert br.allow()       # the probe
    assert not br.allow()   # probes exhausted until an outcome lands
    br.record_success()
    assert br.state == "closed" and br.allow()


def test_breaker_failed_probe_reopens():
    clk = FakeClock()
    br = CircuitBreaker("x", BreakerConfig(failure_threshold=1,
                                           reset_timeout_s=5.0), clock=clk)
    br.record_failure()
    assert br.state == "open"
    clk.advance(5.1)
    assert br.state == "half_open" and br.allow()
    br.record_failure()
    assert br.state == "open" and not br.allow()
    clk.advance(5.1)
    assert br.state == "half_open"  # and the cycle can repeat


def test_breaker_burn_rate_signal():
    clk = FakeClock()
    br = CircuitBreaker("link0", BreakerConfig(failure_threshold=2,
                                               burn_threshold=1.0), clock=clk)
    br.observe_burn(0.3)
    br.observe_burn(2.0)
    assert br.state == "closed"
    br.observe_burn(1.5)
    assert br.state == "open"  # two consecutive over-budget readings


# ---------------------------------------------------------------------------
# admission: typed rejects before any device work
# ---------------------------------------------------------------------------


def test_admission_typed_rejects():
    ctl = AdmissionController(AdmissionConfig(max_queue_depth=4))
    ctl.admit(8, 8, 0, None)  # best-effort always admits below depth
    with pytest.raises(QueueFull):
        ctl.admit(8, 8, 4, None)
    with pytest.raises(DeadlineInfeasible):
        ctl.admit(8, 8, 0, 1e-4)
    assert ctl.rejected_queue_full == 1 and ctl.rejected_deadline == 1


def test_front_records_queue_full_and_deadline_rejects(params):
    front = ServeFront(
        CFG, params,
        config=ServeFrontConfig(admission=AdmissionConfig(max_queue_depth=2)),
        clock=FakeClock())
    p = _prompt()
    for _ in range(2):
        front.submit(Request(prompt_ids=p, max_new_tokens=4))
    rid = front.submit(Request(prompt_ids=p, max_new_tokens=4))
    rec = front.records[-1]
    assert (rec.request_id == rid and rec.outcome == "rejected"
            and rec.reason == "queue_full")

    front2 = ServeFront(CFG, params, clock=FakeClock())
    front2.submit(Request(prompt_ids=p, max_new_tokens=8, deadline_s=1e-4))
    rec = front2.records[-1]
    assert rec.outcome == "rejected" and rec.reason == "deadline_infeasible"
    assert rec.tokens is None  # never touched a device


# ---------------------------------------------------------------------------
# brownout: degrade ladder + dwell hysteresis
# ---------------------------------------------------------------------------


def test_brownout_degrades_and_repromotes_with_dwell():
    clk = FakeClock()
    bo = BrownoutController(BrownoutConfig(degrade_load=0.8,
                                           promote_load=0.2,
                                           min_dwell_s=5.0), clock=clk)
    assert bo.observe(0.9) == 1
    assert bo.observe(0.9) == 1  # dwell holds the level
    clk.advance(5.0)
    assert bo.observe(0.9) == 2
    assert bo.tier_bias == 1 and not bo.hedging_enabled
    clk.advance(5.0)
    assert bo.observe(0.9) == 3
    assert bo.token_cap(8) == 4  # token-cap shrink kicks in
    clk.advance(5.0)
    assert bo.observe(0.9) == 4
    assert bo.should_shed(0) and not bo.should_shed(1)
    # recovery must re-earn each level through the same dwell
    assert bo.observe(0.1) == 4
    clk.advance(5.0)
    assert bo.observe(0.1) == 3
    assert bo.observe(0.1) == 3
    clk.advance(5.0)
    assert bo.observe(0.1) == 2
    assert bo.mode == "hedging_off" and bo.token_cap(8) == 8


def test_front_sheds_lowest_priority_under_brownout(params):
    clk = FakeClock()
    front = ServeFront(
        CFG, params,
        config=ServeFrontConfig(
            brownout=BrownoutConfig(min_dwell_s=1000.0)),
        clock=clk)
    for _ in range(4):
        clk.advance(1000.0)
        front.brownout.observe(1.0)
    assert front.brownout.level == 4
    p = _prompt()
    front.submit(Request(prompt_ids=p, max_new_tokens=4, priority=0))
    rec = front.records[-1]
    assert rec.outcome == "shed" and rec.reason == "brownout_shed"
    depth_before = front.queue_depth
    front.submit(Request(prompt_ids=p, max_new_tokens=4, priority=1))
    assert front.queue_depth == depth_before + 1  # above the floor: queued


# ---------------------------------------------------------------------------
# token identity: the front never changes the math
# ---------------------------------------------------------------------------


def test_front_local_tokens_identical_to_direct_generate(params):
    front = ServeFront(CFG, params, clock=FakeClock())
    p = _prompt(seed=11)
    front.submit(Request(prompt_ids=p, max_new_tokens=6, temperature=0.7,
                         rng_seed=5))
    rec = front.drain()[0]
    assert rec.outcome == "completed"
    ref = generate(CFG, params, jnp.asarray(p), 6, capacity=rec.capacity,
                   temperature=0.7, rng_key=jax.random.key(5))
    assert np.array_equal(rec.tokens, np.asarray(ref))


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
def test_front_split_tokens_identical_to_direct_generate_split(params):
    rt = SplitRuntime(CFG, SplitConfig(cuts=(2,), hop_codecs=("fp32",)),
                      make_stage_mesh(2))
    front = ServeFront(CFG, params, split_runtime=rt, clock=FakeClock())
    p = _prompt(seed=12)
    front.submit(Request(prompt_ids=p, max_new_tokens=6, temperature=0.7,
                         rng_seed=9))
    rec = front.drain()[0]
    assert rec.outcome == "completed" and rec.plan["mode"] == "split"
    ref = generate_split(rt, rt.place_params(params), jnp.asarray(p), 6,
                         capacity=rec.capacity, temperature=0.7,
                         rng_key=jax.random.key(9))
    assert np.array_equal(rec.tokens, np.asarray(ref))


def test_steady_state_is_jit_miss_free(params):
    front = ServeFront(CFG, params, clock=FakeClock())
    for seed in (0, 1):
        front.submit(Request(prompt_ids=_prompt(seed=seed), max_new_tokens=4))
    recs = front.drain()
    assert [r.outcome for r in recs] == ["completed", "completed"]
    assert recs[1].jit_misses == 0  # second same-shape request: compiled plan


# ---------------------------------------------------------------------------
# retry budget: process-wide back-pressure against retry storms
# ---------------------------------------------------------------------------


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
def test_retry_budget_exhaustion_refuses_the_bad_link(params):
    clk = FakeClock()
    rt = SplitRuntime(CFG, SplitConfig(cuts=(2,), hop_codecs=("fp32",)),
                      make_stage_mesh(2),
                      faults=FaultConfig(drop_rate=0.9, seed=0),
                      policy=LinkPolicy(max_retries=4))
    front = ServeFront(
        CFG, params, split_runtime=rt,
        config=ServeFrontConfig(
            retry_budget=RetryBudgetConfig(capacity=1, refill_per_s=0.0),
            local_fallback=False),
        clock=clk)
    p = _prompt(seed=7)
    front.submit(Request(prompt_ids=p, max_new_tokens=4))
    first = front.drain()[0]
    # the forced-bad link burns retries on every hop; the post-hoc charge
    # may overdraw the bucket by at most this one call
    assert first.retries_charged >= 1
    assert front.budget.exhausted()
    front.submit(Request(prompt_ids=p, max_new_tokens=4))
    rec = front.drain()[0]
    assert rec.outcome == "rejected"
    assert rec.reason == "retry_budget_exhausted"
    assert front.budget.denied >= 1
    # spending stopped: the refused request charged nothing
    assert front.budget.spent == first.retries_charged


# ---------------------------------------------------------------------------
# the deterministic chaos soak
# ---------------------------------------------------------------------------


@pytest.mark.skipif(len(jax.devices()) < 3, reason="needs 3 devices")
def test_soak_survives_stage_kill_and_corruption_burst(params):
    clk = FakeClock()
    split = SplitConfig(cuts=(1, 3), hop_codecs=("fp32", "fp32"))
    mesh = make_stage_mesh(3)
    policy = LinkPolicy(max_retries=4)
    rt = SplitRuntime(CFG, split, mesh,
                      faults=FaultConfig(drop_rate=0.02, seed=0),
                      policy=policy)
    burst = SplitRuntime(CFG, split, mesh,
                         faults=FaultConfig(drop_rate=0.2, seed=0),
                         policy=policy)
    front = ServeFront(CFG, params, split_runtime=rt, clock=clk)
    soak = SoakConfig(n_requests=10, arrival_rate=0.5, prompt_len=8,
                      max_new_tokens=6, deadline_s=120.0, kill_stage=1)
    art = run_soak(front, soak, clock=clk, burst_runtime=burst)

    assert art["requests"] == 10
    assert art["outcomes"].get("failed_over", 0) >= 1  # the kill was felt
    assert art["kill"]["recovery_s"] is not None       # and recovered from
    # the contract the soak exists to enforce: completed == bit-identical
    # to the fault-free reference, and retries stayed inside the budget
    identity = art["token_identity"]
    assert identity["checked"] > 0 and identity["ok"]
    assert art["retry_budget"]["within_budget"]
    assert art["goodput_tokens_per_s"] > 0
    # the replanned boundary persists: the front now serves 2 stages
    assert front.split_runtime.split.n_stages == 2


def test_soak_requires_the_fronts_fake_clock(params):
    front = ServeFront(CFG, params, clock=FakeClock())
    with pytest.raises(TypeError):
        run_soak(front, SoakConfig(n_requests=1), clock=None)


# ---------------------------------------------------------------------------
# health_summary: one consistent snapshot under the submit lock
# ---------------------------------------------------------------------------


def test_health_summary_snapshot_consistent_under_all_interleavings(params):
    """Bounded schedule exploration (threadlint harness): race ``drain``
    against ``health_summary`` over every interleaving of their submit-lock
    critical sections. The one submitted request must be in EXACTLY one
    place per snapshot — queue, inflight, or the record aggregate. A torn
    (pre-fix, lock-free) summary can read the queue after the pop but the
    aggregate before the finish and report a request that exists nowhere
    (sum 0), or both halves (sum 2)."""
    from edgellm_tpu.lint.schedules import explore, instrument

    def scenario(sched):
        clk = FakeClock()
        front = ServeFront(CFG, params, clock=clk)
        # an already-expired deadline: drain takes the expired_in_queue
        # path — pure bookkeeping, no device work under the scheduler
        front.submit(Request(prompt_ids=_prompt(seed=11), max_new_tokens=4,
                             deadline_s=5.0))
        clk.advance(30.0)
        instrument(sched, front, "_submit_lock")
        snapshots = []

        def verify():
            for h in snapshots:
                total = h["queue_depth"] + h["inflight"] + h["records"]
                assert total == 1, f"torn snapshot: {h}"

        return ([lambda: front.drain(),
                 lambda: snapshots.append(front.health_summary())], verify)

    outcomes = explore(scenario, max_preemptions=2)
    assert len(outcomes) > 1          # the bound really explored schedules
    assert not any(o.deadlocked for o in outcomes), \
        [o.blocked for o in outcomes if o.deadlocked]
    assert not any(o.errors for o in outcomes), \
        [o.errors for o in outcomes if o.errors]
