"""Whole-S-in-VMEM attention kernel: parity vs the dense formulation (the
kernel runs in interpret mode on CPU; on TPU it is the default hot path for
S <= 1024 — measured ~2.4x XLA's fused attention at the flagship shapes)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.models.flash_attention import (causal_attention,
                                                causal_attention_stats,
                                                kernel_eligible)


def _dense(q, k, v):
    b, s, h, hd = q.shape
    rep = h // k.shape[2]
    k = np.repeat(k, rep, axis=2)
    v = np.repeat(v, rep, axis=2)
    scores = np.einsum("bshd,bthd->bhst", q, k, dtype=np.float32) / np.sqrt(hd)
    mask = np.tril(np.ones((s, s), bool))
    scores = np.where(mask[None, None], scores, -np.inf)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    out = np.einsum("bhst,bthd->bshd", p, v)
    return out, p


@pytest.mark.parametrize("b,h,kv,s,hd", [
    (2, 4, 4, 64, 32),    # MHA
    (2, 4, 2, 64, 32),    # GQA rep=2
    (1, 14, 2, 32, 64),   # the flagship head layout
    (3, 8, 8, 24, 16),    # s not a power of two
])
def test_kernel_matches_dense(rng, b, h, kv, s, hd):
    q = rng.normal(size=(b, s, h, hd)).astype(np.float32)
    k = rng.normal(size=(b, s, kv, hd)).astype(np.float32)
    v = rng.normal(size=(b, s, kv, hd)).astype(np.float32)
    want, _ = _dense(q, k, v)
    got = causal_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=2e-5)


def test_stats_kernel_matches_full_probs(rng):
    b, h, s, hd = 2, 4, 64, 32
    q = rng.normal(size=(b, s, h, hd)).astype(np.float32)
    k = rng.normal(size=(b, s, 2, hd)).astype(np.float32)
    v = rng.normal(size=(b, s, 2, hd)).astype(np.float32)
    want_out, p = _dense(q, k, v)
    out, (col, last) = causal_attention_stats(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), interpret=True)
    np.testing.assert_allclose(np.asarray(out), want_out, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(col), p.sum(axis=2) / s, atol=1e-6)
    np.testing.assert_allclose(np.asarray(last), p[:, :, -1, :], atol=1e-6)


def test_model_attention_same_under_either_backend(rng, monkeypatch):
    """Forcing the kernel into transformer.attention (EDGELLM_ATTN=pallas,
    interpret on CPU) reproduces the default path's block output and stats."""
    from edgellm_tpu.models import tiny_config, init_params
    from edgellm_tpu.models.transformer import forward, run_layers_from_ids

    cfg = tiny_config("qwen2", num_layers=3, hidden_size=64, num_heads=4,
                      vocab_size=128)
    params = init_params(cfg, jax.random.key(0))
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 32)))

    monkeypatch.setenv("EDGELLM_ATTN", "xla")
    base, _ = forward(cfg, params, ids)
    _, aux = run_layers_from_ids(cfg, params, ids, capture_stats=True)
    jax.clear_caches()  # attention() branches on env at trace time

    monkeypatch.setenv("EDGELLM_ATTN", "pallas")
    got, _ = forward(cfg, params, ids)
    _, aux_p = run_layers_from_ids(cfg, params, ids, capture_stats=True)
    jax.clear_caches()
    np.testing.assert_allclose(np.asarray(got), np.asarray(base),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(aux_p["stats"].col_mean),
                               np.asarray(aux["stats"].col_mean), atol=1e-5)
    np.testing.assert_allclose(np.asarray(aux_p["stats"].last_row),
                               np.asarray(aux["stats"].last_row), atol=1e-5)


def test_kernel_eligibility(monkeypatch):
    monkeypatch.delenv("EDGELLM_ATTN", raising=False)
    # CPU default: no kernel (interpret mode would be slow, XLA is fine)
    assert not kernel_eligible(512, 896)
    monkeypatch.setenv("EDGELLM_ATTN", "pallas")
    assert kernel_eligible(512, 896)
    assert kernel_eligible(512, 1536)   # qwen2-1.5b: measured 3.4x win
    assert not kernel_eligible(2048, 896)  # whole-S scores would blow VMEM
    assert not kernel_eligible(512, 2048)  # llama-1b row: scoped-VMEM OOM
    monkeypatch.setenv("EDGELLM_ATTN", "xla")
    assert not kernel_eligible(512, 896)