"""Whole-S-in-VMEM attention kernel: parity vs the dense formulation (the
kernel runs in interpret mode on CPU; on TPU it is the default hot path for
S <= 1024 — measured ~2.4x XLA's fused attention at the flagship shapes)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from edgellm_tpu.models.flash_attention import (causal_attention,
                                                causal_attention_stats,
                                                kernel_eligible,
                                                kernel_plan,
                                                _shape_plan)


def _dense(q, k, v):
    b, s, h, hd = q.shape
    rep = h // k.shape[2]
    k = np.repeat(k, rep, axis=2)
    v = np.repeat(v, rep, axis=2)
    scores = np.einsum("bshd,bthd->bhst", q, k, dtype=np.float32) / np.sqrt(hd)
    mask = np.tril(np.ones((s, s), bool))
    scores = np.where(mask[None, None], scores, -np.inf)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    out = np.einsum("bhst,bthd->bshd", p, v)
    return out, p


@pytest.mark.parametrize("b,h,kv,s,hd", [
    (2, 4, 4, 64, 32),    # MHA
    (2, 4, 2, 64, 32),    # GQA rep=2
    (1, 14, 2, 32, 64),   # the flagship head layout
    (3, 8, 8, 24, 16),    # s not a power of two
])
def test_kernel_matches_dense(rng, b, h, kv, s, hd):
    q = rng.normal(size=(b, s, h, hd)).astype(np.float32)
    k = rng.normal(size=(b, s, kv, hd)).astype(np.float32)
    v = rng.normal(size=(b, s, kv, hd)).astype(np.float32)
    want, _ = _dense(q, k, v)
    got = causal_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=2e-5)


def test_stats_kernel_matches_full_probs(rng):
    b, h, s, hd = 2, 4, 64, 32
    q = rng.normal(size=(b, s, h, hd)).astype(np.float32)
    k = rng.normal(size=(b, s, 2, hd)).astype(np.float32)
    v = rng.normal(size=(b, s, 2, hd)).astype(np.float32)
    want_out, p = _dense(q, k, v)
    out, (col, last) = causal_attention_stats(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), interpret=True)
    np.testing.assert_allclose(np.asarray(out), want_out, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(col), p.sum(axis=2) / s, atol=1e-6)
    np.testing.assert_allclose(np.asarray(last), p[:, :, -1, :], atol=1e-6)


def test_model_attention_same_under_either_backend(rng, monkeypatch):
    """Forcing the kernel into transformer.attention (EDGELLM_ATTN=pallas,
    interpret on CPU) reproduces the default path's block output and stats."""
    from edgellm_tpu.models import tiny_config, init_params
    from edgellm_tpu.models.transformer import forward, run_layers_from_ids

    # hd must be in VALIDATED_HD (64) or the pallas force would silently take
    # the XLA path and this test would compare XLA against XLA
    cfg = tiny_config("qwen2", num_layers=3, hidden_size=256, num_heads=4,
                      vocab_size=128)
    params = init_params(cfg, jax.random.key(0))
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 32)))

    monkeypatch.setenv("EDGELLM_ATTN", "xla")
    base, _ = forward(cfg, params, ids)
    _, aux = run_layers_from_ids(cfg, params, ids, capture_stats=True)
    jax.clear_caches()  # attention() branches on env at trace time

    monkeypatch.setenv("EDGELLM_ATTN", "pallas")
    got, _ = forward(cfg, params, ids)
    _, aux_p = run_layers_from_ids(cfg, params, ids, capture_stats=True)
    jax.clear_caches()
    np.testing.assert_allclose(np.asarray(got), np.asarray(base),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(aux_p["stats"].col_mean),
                               np.asarray(aux["stats"].col_mean), atol=1e-5)
    np.testing.assert_allclose(np.asarray(aux_p["stats"].last_row),
                               np.asarray(aux["stats"].last_row), atol=1e-5)


def test_kernel_eligibility(monkeypatch):
    monkeypatch.delenv("EDGELLM_ATTN", raising=False)
    # CPU default: no kernel (interpret mode would be slow, XLA is fine)
    with pytest.warns(DeprecationWarning, match="kernel_plan"):
        assert not kernel_eligible(512, 896)
    # explicit head counts: no layout inference, no warning
    assert not kernel_eligible(512, 896, num_heads=14, num_kv_heads=2)
    monkeypatch.setenv("EDGELLM_ATTN", "pallas")
    assert kernel_plan(512, 14, 2, 64) == ("whole", None)   # flagship
    assert kernel_plan(512, 12, 2, 128) == ("whole", None)  # qwen2-1.5b
    # S=2048 — the reference's Pythia window: query-blocked kernel
    assert kernel_plan(2048, 8, 8, 64) == ("blocked", (512, 8))
    assert kernel_plan(2048, 14, 2, 64) == ("blocked", (512, 14))
    # llama-1b: packed row 2048 > whole-kernel envelope -> head-group split
    assert kernel_plan(512, 32, 8, 64) == ("blocked", (512, 16))
    assert kernel_plan(2048, 32, 8, 64) == ("blocked", (512, 16))
    # beyond the blocked envelope, unvalidated hd, ragged GQA: XLA
    assert kernel_plan(4096, 8, 8, 64) is None
    assert kernel_plan(512, 8, 8, 80) is None      # ADVICE r4: hd gate
    assert kernel_plan(512, 14, 4, 64) is None     # H % KV != 0
    assert kernel_plan(1536, 8, 8, 64) == ("blocked", (512, 8))
    assert kernel_plan(1100, 8, 8, 64) is None     # S not qb-aligned
    monkeypatch.setenv("EDGELLM_ATTN", "xla")
    with pytest.warns(DeprecationWarning, match="kernel_plan"):
        assert not kernel_eligible(512, 896)


def test_shape_plan_scales_whole_s_by_itemsize():
    """ADVICE r5 #1: the whole-S VMEM envelope assumes bf16 rows; wider
    dtypes shrink the eligible S/packed-dh and fall through to the blocked
    plan (whose K/V budget is already itemsize-aware)."""
    assert _shape_plan(1024, 12, 2, 128) == ("whole", None)          # bf16
    assert _shape_plan(1024, 12, 2, 128, itemsize=4) != ("whole", None)
    assert _shape_plan(512, 12, 2, 64, itemsize=4) == ("whole", None)
    # packed-dh gate: fp32 halves the 1536-lane row budget too
    assert _shape_plan(512, 14, 2, 96)[0] == "whole"                 # dh=1344
    assert _shape_plan(512, 14, 2, 96, itemsize=4)[0] != "whole"


@pytest.mark.parametrize("b,h,kv,s,hd,qb,hps", [
    (2, 4, 4, 128, 32, 32, 4),   # query-blocked, all heads per step
    (2, 4, 2, 128, 32, 64, 2),   # query-blocked + GQA head-group split
    (1, 8, 2, 64, 32, 64, 4),    # head-group split only (qb == S)
    (2, 4, 4, 96, 16, 32, 2),    # both splits, MHA
])
def test_blocked_kernel_matches_dense(rng, b, h, kv, s, hd, qb, hps):
    q = rng.normal(size=(b, s, h, hd)).astype(np.float32)
    k = rng.normal(size=(b, s, kv, hd)).astype(np.float32)
    v = rng.normal(size=(b, s, kv, hd)).astype(np.float32)
    want, p = _dense(q, k, v)
    plan = ("blocked", (qb, hps))
    got = causal_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           interpret=True, plan=plan)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=2e-5)
    out, (col, last) = causal_attention_stats(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        interpret=True, plan=plan)
    np.testing.assert_allclose(np.asarray(out), want, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(col), p.sum(axis=2) / s, atol=1e-5)
    np.testing.assert_allclose(np.asarray(last), p[:, :, -1, :], atol=1e-6)


def test_blocked_plan_is_auto_resolved(rng):
    """At a shape outside the whole-S envelope, causal_attention resolves the
    blocked plan itself (what the model's TPU dispatch relies on)."""
    assert _shape_plan(128, 4, 2, 32) == ("whole", None)
    b, s, h, kv, hd = 1, 1536, 4, 2, 32
    # force the blocked path by shape: s > MAX_WHOLE_S
    q = rng.normal(size=(b, s, h, hd)).astype(np.float32)
    k = rng.normal(size=(b, s, kv, hd)).astype(np.float32)
    v = rng.normal(size=(b, s, kv, hd)).astype(np.float32)
    want, _ = _dense(q, k, v)
    got = causal_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5, rtol=2e-5)