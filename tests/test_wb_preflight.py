"""AOT window-batch preflight: estimates scale with the batch and the halving
search lands on the largest candidate under the budget — all without touching
device memory (compile-only)."""
import jax.numpy as jnp

from edgellm_tpu.models import tiny_config
from edgellm_tpu.tools.wb_preflight import (estimate_sweep_peak_bytes,
                                            largest_fitting_window_batch)

CFG = tiny_config("qwen2", num_layers=4, hidden_size=32, num_heads=4,
                  vocab_size=128)
KW = dict(max_length=32, tail=9, layer=1, codec="int4_token_select",
          n_ratios=3, dtype=jnp.float32)


def test_estimate_grows_with_batch():
    small = estimate_sweep_peak_bytes(CFG, 2, **KW)
    big = estimate_sweep_peak_bytes(CFG, 8, **KW)
    assert big["peak"] > small["peak"]
    assert big["hiddens_stack"] == 4 * small["hiddens_stack"]
    for key in ("stats_call", "suffix_call", "peak"):
        assert small[key] > 0


def test_halving_respects_budget():
    est8 = estimate_sweep_peak_bytes(CFG, 8, **KW)
    est2 = estimate_sweep_peak_bytes(CFG, 2, **KW)
    # budget between the 2- and 8-window peaks -> search must settle below 8
    budget = (est2["peak"] + est8["peak"]) // 2
    wb, est = largest_fitting_window_batch(CFG, 8, hbm_bytes=budget,
                                           budget_frac=1.0, **KW)
    assert wb < 8 and est["peak"] <= budget


def test_min_window_batch_floor():
    wb, _ = largest_fitting_window_batch(CFG, 8, hbm_bytes=1, budget_frac=1.0,
                                         **KW)
    assert wb == 1  # nothing fits: floor, never an infinite loop


def test_relevance_preflight_halves_to_fit():
    from edgellm_tpu.tools.wb_preflight import largest_fitting_relevance_batch

    big = largest_fitting_relevance_batch(CFG, 8, max_length=32,
                                          dtype=jnp.float32,
                                          hbm_bytes=1 << 40, budget_frac=1.0)
    assert big == 8  # everything fits under a huge budget
    tiny = largest_fitting_relevance_batch(CFG, 8, max_length=32,
                                           dtype=jnp.float32,
                                           hbm_bytes=1, budget_frac=1.0)
    assert tiny == 1


def test_token_sweep_preflight_uses_earliest_layer():
    """The shared sweep wrapper sizes the longest suffix (earliest layer) and
    the dedup-aware ratio axis; a generous budget keeps the requested batch."""
    from edgellm_tpu.tools.wb_preflight import preflight_token_sweep_batch

    wb = preflight_token_sweep_batch(CFG, 4, max_length=32, stride=8,
                                     layers_of_interest=[2, 1], ratios=[0, 0.5],
                                     dtype=jnp.float32, hbm_bytes=1 << 40,
                                     budget_frac=1.0)
    assert wb == 4
    tiny = preflight_token_sweep_batch(CFG, 4, max_length=32, stride=8,
                                       layers_of_interest=[2, 1],
                                       ratios=[0, 0.5], dtype=jnp.float32,
                                       hbm_bytes=1, budget_frac=1.0)
    assert tiny == 1
