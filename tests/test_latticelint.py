"""latticelint test coverage: the pair oracle matches run.py's validator
message-for-message, the documented invalid feature combos die with their
exact typed errors, README parity / donation / budget checks each catch a
seeded-bad fixture with exactly one finding, and capability_matrix.json has
the documented shape.

The full 26-config AOT sweep is the slow CLI acceptance test at the bottom
(CI's latticelint job runs the same command as the required gate); the
tier-1 tests here use either pure validation or a two-config fixture
directory so they stay cheap.
"""
import dataclasses
import json
import os
import pathlib
import subprocess
import sys

import pytest

from edgellm_tpu.lint import lattice
from edgellm_tpu.lint.lattice import (MATRIX_SCHEMA, PAIR_ORACLE,
                                      compose_combo, donation_findings,
                                      readme_parity_findings,
                                      run_lattice_checks, write_matrix)

REPO = pathlib.Path(__file__).resolve().parent.parent
CONFIGS = REPO / "configs"


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# the pair oracle is exact, both directions
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("pair", sorted(PAIR_ORACLE))
def test_pair_oracle_matches_validator(pair):
    """Every refused pair dies with the exact message the oracle pins."""
    assert lattice._validate(compose_combo(pair)) == PAIR_ORACLE[pair]


@pytest.mark.parametrize("name", sorted(lattice.FUZZ_BLOCKS))
def test_every_feature_block_validates_alone(name):
    assert lattice._validate(compose_combo((name,))) is None


def _fec_fields():
    from edgellm_tpu.codecs.fec import FECConfig

    return sorted(f.name for f in dataclasses.fields(FECConfig))


# the user-facing refusals REPRODUCING documents, with their exact text —
# a reworded die() that forgets this table is a test failure, a reworded
# die() that forgets PAIR_ORACLE is an LL-compat finding
_DOC_COMBOS = [
    ("spec+batching",
     compose_combo(("batching", "speculative")),
     "speculative runs the one-stream spec loop; the batcher's ragged step "
     "verifies one token per slot — drop 'speculative' or 'batching'"),
    ("cluster without batching",
     {"experiment": "serve", "serving": {},
      "cluster": {"num_replicas": 2}},
     "cluster replicas each run the continuous batcher — add a 'batching' "
     "block"),
    ("disagg+speculative",
     {"experiment": "serve", "serving": {},
      "cuts": [2], "hop_codecs": ["int8_per_token"],
      "speculative": {"k": 4}, "disagg": {"num_prefill_workers": 1}},
     "disagg + speculative: the spec loop is single-stream with no "
     "prefill/decode split story — drop one of the two blocks"),
    ("nested fec in disagg, unknown field",
     {"experiment": "serve", "serving": {},
      "batching": {"page_size": 8, "num_pages": 10, "max_slots": 2,
                   "pages_per_slot": 2},
      "disagg": {"fec": {"bogus": 1}}},
     f"disagg.fec: unknown field(s) ['bogus']; known: {_fec_fields()}"),
]


@pytest.mark.parametrize("label,params,message",
                         _DOC_COMBOS, ids=[c[0] for c in _DOC_COMBOS])
def test_documented_invalid_combos_exact_errors(label, params, message):
    assert lattice._validate(params) == message


def test_budget_block_validation():
    base = {"experiment": "serve", "serving": {}}

    def msg(budget):
        return lattice._validate({**base, "budget": budget})

    assert msg({"aot_peak_bytes": 1}) is None
    assert msg({"aot_peak_bytes": 1, "note": "why"}) is None
    assert "must be an object" in msg([1])
    assert "unknown field(s) ['typo']" in msg({"aot_peak_bytes": 1,
                                              "typo": 0})
    assert "needs 'aot_peak_bytes'" in msg({"note": "empty"})
    assert "positive integer" in msg({"aot_peak_bytes": 0})
    assert "positive integer" in msg({"aot_peak_bytes": True})
    assert "must be a string" in msg({"aot_peak_bytes": 1, "note": 3})


# ---------------------------------------------------------------------------
# shipped configs: all valid, all budgeted, README in sync
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("path", sorted(CONFIGS.glob("*.json")),
                         ids=lambda p: p.stem)
def test_shipped_config_validates_with_budget(path):
    p = json.loads(path.read_text())
    assert lattice._validate(p) is None
    assert p["budget"]["aot_peak_bytes"] > 0


def test_shipped_readme_in_sync():
    assert readme_parity_findings(CONFIGS) == []


def test_readme_parity_catches_seeded_drift(tmp_path):
    (tmp_path / "a.json").write_text("{}")
    (tmp_path / "b.json").write_text("{}")
    (tmp_path / "README.md").write_text(
        "| config | target |\n|---|---|\n"
        "| `a.json` | real, produces `artifact.json` |\n"
        "| `ghost.json` | deleted config, stale row |\n")
    findings = readme_parity_findings(tmp_path)
    assert _rules(findings) == [lattice.RULE_README, lattice.RULE_README]
    assert findings[0].message == "configs/b.json has no README table row"
    assert findings[1].message == ("README mentions `ghost.json` but "
                                   "configs/ghost.json does not exist")
    # `artifact.json` in the description cell is NOT a registration: only
    # the first column names configs (the relevance row mentions its
    # produced attention_head_weights.json the same way)


# ---------------------------------------------------------------------------
# seeded missing donation: exactly one LL-donate finding
# ---------------------------------------------------------------------------


def test_donation_finding_on_stripped_donate_argnums():
    import jax
    import jax.numpy as jnp

    def step(cache, tok):
        return cache.at[0].add(tok), tok * 2

    args = (jnp.zeros((4, 4)), jnp.ones((4,)))
    donated = jax.jit(step, donate_argnums=(0,))
    assert donation_findings(donated, args, 1, "fixture") == []

    stripped = jax.jit(step)  # the seeded bug: donate_argnums dropped
    findings = donation_findings(stripped, args, 1, "fixture")
    assert _rules(findings) == [lattice.RULE_DONATE]
    assert "donates 0 input buffer(s), needs >= 1" in findings[0].message


# ---------------------------------------------------------------------------
# seeded budget drift: one finding per bad config, clean twin stays clean
# ---------------------------------------------------------------------------


def _sweep_fixture(tmp_path, budgets):
    """A tiny token-sweep config per (name -> budget block or None), plus a
    README that keeps parity quiet. All share one plan geometry, so the
    lattice compiles the sweep entry points once."""
    rows = ""
    for name, budget in budgets.items():
        p = {"ratios": [0], "layers_of_interest": [1],
             "methods": ["regular_importance"], "max_length": 64,
             "stride": 32}
        if budget is not None:
            p["budget"] = budget
        (tmp_path / f"{name}.json").write_text(json.dumps(p))
        rows += f"| `{name}.json` | fixture |\n"
    (tmp_path / "README.md").write_text(
        "| config | target |\n|---|---|\n" + rows)
    return tmp_path


def test_budget_fixtures_each_one_finding(tmp_path):
    configs_dir = _sweep_fixture(tmp_path, {
        "clean": {"aot_peak_bytes": 1 << 24},
        "over": {"aot_peak_bytes": 1},   # seeded: peak can't fit in 1 byte
        "nobudget": None,                # seeded: block missing entirely
    })
    findings, checked, _, matrix = run_lattice_checks(
        configs_dir=configs_dir, pairwise=False)
    by_stem = {pathlib.Path(f.where).stem: f for f in findings}
    assert set(by_stem) == {"over", "nobudget"}
    assert _rules(findings) == [lattice.RULE_BUDGET, lattice.RULE_BUDGET]
    assert "exceeds the config's budget of 1 bytes" in by_stem[
        "over"].message
    assert 'missing "budget" block' in by_stem["nobudget"].message
    assert "lattice.config:clean" in checked
    assert "lattice.readme-parity" in checked

    # the matrix records the measured peak either way
    over = matrix["configs"]["over"]
    assert over["peak_bytes"] > 1 and over["budget_bytes"] == 1
    assert matrix["configs"]["clean"]["peak_bytes"] == over["peak_bytes"]
    assert matrix["configs"]["nobudget"]["budget_bytes"] is None

    # matrix shape: the documented v1 schema
    assert matrix["schema"] == MATRIX_SCHEMA
    assert set(matrix) == {"schema", "tiny_geometry", "configs", "pairs"}
    geo = matrix["tiny_geometry"]
    assert geo["model"] == "qwen2-tiny" and geo["batch"] == 1
    for rec in matrix["configs"].values():
        assert set(rec) == {"features", "experiment", "valid", "refusal",
                            "entrypoints", "donation", "notes",
                            "peak_bytes", "budget_bytes"}
        assert rec["valid"] and rec["refusal"] is None
        for cost in rec["entrypoints"].values():
            assert cost["total_bytes"] == (cost["argument_bytes"]
                                           + cost["output_bytes"]
                                           + cost["temp_bytes"])

    out = tmp_path / "matrix.json"
    write_matrix(matrix, str(out))
    assert json.loads(out.read_text()) == json.loads(
        json.dumps(matrix))  # write_matrix round-trips losslessly


# ---------------------------------------------------------------------------
# seeded validator/oracle drift: exactly one LL-compat finding per direction
# ---------------------------------------------------------------------------


class _NoLowerWorld:
    """Stand-in world for validation-only drift tests: accepted combos are
    not lowered (the build half of the drift check runs in the slow CLI
    acceptance test over the real world)."""

    def plan(self, p):
        return [], []


def _drift_findings(monkeypatch, oracle, blocks=("pipeline", "speculative")):
    monkeypatch.setattr(lattice, "FUZZ_BLOCKS",
                        {k: lattice.FUZZ_BLOCKS[k]
                         for k in (*blocks, "cuts")})
    findings = []
    lattice._pair_sweep(_NoLowerWorld(), findings, oracle)
    return findings


def test_drift_stale_oracle_message(monkeypatch):
    stale = {("pipeline", "speculative"): "stale text run.py never emits"}
    findings = _drift_findings(monkeypatch, stale)
    assert _rules(findings) == [lattice.RULE_COMPAT]
    assert "refused with a different message" in findings[0].message


def test_drift_validator_refuses_unpinned_pair(monkeypatch):
    findings = _drift_findings(monkeypatch, {})  # oracle lost the entry
    assert _rules(findings) == [lattice.RULE_COMPAT]
    assert ("combo pipeline+speculative should validate but run.py "
            "refuses it" in findings[0].message)


def test_drift_validator_accepts_pinned_pair(monkeypatch):
    oracle = {("cuts", "pipeline"): "pinned but the check was deleted",
              ("pipeline", "speculative"):
                  PAIR_ORACLE[("pipeline", "speculative")]}
    findings = _drift_findings(monkeypatch, oracle)
    assert _rules(findings) == [lattice.RULE_COMPAT]
    assert ("combo cuts+pipeline should be refused" in findings[0].message
            and "but run.py accepts it" in findings[0].message)


def test_drift_clean_oracle_no_findings(monkeypatch):
    oracle = {("pipeline", "speculative"):
              PAIR_ORACLE[("pipeline", "speculative")]}
    assert _drift_findings(monkeypatch, oracle) == []


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _run_cli(*args, **kw):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "edgellm_tpu.lint", *args],
        capture_output=True, text=True, timeout=kw.pop("timeout", 300),
        env=env, cwd=str(REPO))


def test_cli_lattice_only_is_exclusive():
    proc = _run_cli("--lattice-only", "--ast-only")
    assert proc.returncode == 2
    assert "mutually exclusive" in proc.stderr


def test_cli_lattice_only_refuses_paths():
    proc = _run_cli("--lattice-only", "edgellm_tpu/run.py")
    assert proc.returncode == 2
    assert "lints configs/" in proc.stderr


@pytest.mark.slow
def test_cli_lattice_only_clean_on_real_configs(tmp_path):
    """Acceptance: the lattice layer alone exits 0 over the shipped configs
    and emits the full report/SARIF/matrix artifact set — the exact command
    CI's latticelint job gates on."""
    report = tmp_path / "report.json"
    sarif = tmp_path / "lattice.sarif"
    matrix = tmp_path / "capability_matrix.json"
    proc = _run_cli("--lattice-only", "--json", str(report),
                    "--sarif", str(sarif), "--matrix", str(matrix),
                    timeout=580)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    rep = json.loads(report.read_text())
    n_configs = len(list(CONFIGS.glob("*.json")))
    assert rep["ok"]
    covered = [c for c in rep["checked_contracts"]
               if c.startswith("lattice.config:")]
    assert len(covered) == n_configs
    assert "lattice.readme-parity" in rep["checked_contracts"]
    assert "lattice.pairwise-compat" in rep["checked_contracts"]

    doc = json.loads(sarif.read_text())
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["results"] == []

    m = json.loads(matrix.read_text())
    assert m["schema"] == MATRIX_SCHEMA
    assert len(m["configs"]) == n_configs
    assert all(rec["valid"] for rec in m["configs"].values())
    assert all(rec["budget_bytes"] and rec["peak_bytes"]
               <= rec["budget_bytes"] for rec in m["configs"].values()
               if rec["peak_bytes"] is not None)
    # every refused fuzz pair carries run.py's exact message
    refused = {k: v["refusal"] for k, v in m["pairs"].items()
               if not v["ok"]}
    for (a, b), msg in PAIR_ORACLE.items():
        assert refused[f"{a}+{b}"] == msg
