"""Pathological-input robustness for every wire codec: NaN / Inf / huge
activations must round-trip to FINITE, DETERMINISTIC output (or raise) —
never silent garbage on the wire. The sanitize contract: non-finite values
become 0, magnitudes saturate at min(SATURATE_MAG, dtype max).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from edgellm_tpu.codecs.packing import (WIRE_CODECS, SATURATE_MAG,
                                        get_wire_codec, sanitize_hidden,
                                        selective_int4)

SHAPE = (2, 8, 24)


def _pathological(rng):
    base = rng.normal(size=SHAPE).astype(np.float32)
    nan = base.copy()
    nan[0, :, 0] = np.nan
    inf = base.copy()
    inf[0, 1, :] = np.inf
    inf[1, 2, :] = -np.inf
    huge = np.where(base > 0, 3e38, -3e38).astype(np.float32)
    mixed = base.copy()
    mixed[0, 0, 0] = np.nan
    mixed[0, 0, 1] = np.inf
    mixed[1, -1, -1] = -np.inf
    mixed[1, 0, 0] = 3e38
    return {"all_nan": np.full(SHAPE, np.nan, np.float32), "some_nan": nan,
            "inf_rows": inf, "huge": huge, "mixed": mixed,
            "zeros": np.zeros(SHAPE, np.float32)}


@pytest.mark.parametrize("name", WIRE_CODECS)
def test_pathological_roundtrip_finite_and_deterministic(name, rng):
    codec = get_wire_codec(name)
    for case, arr in _pathological(rng).items():
        h = jnp.asarray(arr)
        out1 = np.asarray(codec.decode(codec.encode(h)))
        out2 = np.asarray(codec.decode(codec.encode(h)))
        assert out1.shape == SHAPE, f"{name}/{case}"
        assert np.isfinite(out1).all(), \
            f"{name}/{case}: non-finite values crossed the wire"
        np.testing.assert_array_equal(out1, out2,
                                      err_msg=f"{name}/{case} nondeterministic")


@pytest.mark.parametrize("ratio,high", [(0.5, "bf16"), (0.25, "fp16")])
def test_selective_codec_pathological(ratio, high, rng):
    codec = selective_int4(ratio, high)
    imp = jnp.asarray(rng.uniform(size=SHAPE[:2]).astype(np.float32))
    for case, arr in _pathological(rng).items():
        h = jnp.asarray(arr)
        out1 = np.asarray(codec.decode(codec.encode(h, imp)))
        out2 = np.asarray(codec.decode(codec.encode(h, imp)))
        assert np.isfinite(out1).all(), f"{ratio}/{high}/{case}"
        np.testing.assert_array_equal(out1, out2)


def test_sanitize_hidden_contract():
    h = jnp.asarray([np.nan, np.inf, -np.inf, 2e38, -2e38, 1.5, 0.0],
                    jnp.float32)
    out = np.asarray(sanitize_hidden(h))
    np.testing.assert_array_equal(
        out, np.asarray([0.0, SATURATE_MAG, -SATURATE_MAG, SATURATE_MAG,
                         -SATURATE_MAG, 1.5, 0.0], np.float32))


def test_fp16_codec_saturates_to_dtype_max():
    codec = get_wire_codec("fp16")
    h = jnp.full(SHAPE, 1e30, jnp.float32)
    out = np.asarray(codec.decode(codec.encode(h)))
    assert np.isfinite(out).all()
    assert np.all(out == np.float32(np.finfo(np.float16).max))


def test_huge_but_finite_scales_do_not_poison_quantized_codecs(rng):
    """A single huge outlier must not turn the rest of the row into NaN."""
    arr = rng.normal(size=SHAPE).astype(np.float32)
    arr[0, 0, 0] = 1e38
    for name in ("int8_per_token", "int4_per_token", "ternary_per_token"):
        out = np.asarray(get_wire_codec(name).decode(
            get_wire_codec(name).encode(jnp.asarray(arr))))
        assert np.isfinite(out).all(), name
